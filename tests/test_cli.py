"""CLI driver tests: flag surface parity (reference main.py:37-81), flag →
TrainConfig mapping, metric sinks, and an end-to-end smoke train through
``main()`` on a synthetic corpus."""

import json
import os

import pytest

from code2vec_tpu.cli import build_parser, config_from_args, main, sinks_from_args
from code2vec_tpu.data.synth import SPECS, generate_corpus_files
from code2vec_tpu.sinks import floyd_sink


@pytest.fixture(scope="module")
def corpus_files(tmp_path_factory):
    out = tmp_path_factory.mktemp("cli_corpus")
    return generate_corpus_files(out, SPECS["tiny"])


# every flag the reference's argparse block defines (main.py:37-81)
REFERENCE_FLAGS = [
    "random_seed", "corpus_path", "path_idx_path", "terminal_idx_path",
    "batch_size", "terminal_embed_size", "path_embed_size", "encode_size",
    "max_path_length", "model_path", "vectors_path", "test_result_path",
    "max_epoch", "lr", "beta_min", "beta_max", "weight_decay",
    "dropout_prob", "no_cuda", "gpu", "num_workers", "env",
    "print_sample_cycle", "eval_method", "find_hyperparams", "num_trials",
    "angular_margin_loss", "angular_margin", "inverse_temp",
    "infer_method_name", "infer_variable_name", "shuffle_variable_indexes",
]


class TestFlagSurface:
    def test_every_reference_flag_exists(self):
        args = build_parser().parse_args([])
        for flag in REFERENCE_FLAGS:
            assert hasattr(args, flag), f"missing reference flag --{flag}"

    def test_reference_defaults_preserved(self):
        args = build_parser().parse_args([])
        assert args.random_seed == 123
        assert args.batch_size == 32
        assert args.encode_size == 300
        assert args.max_path_length == 200
        assert args.lr == 0.01
        assert args.dropout_prob == 0.25
        assert args.max_epoch == 40
        assert args.eval_method == "subtoken"
        assert args.angular_margin == 0.5
        assert args.inverse_temp == 30.0
        assert args.infer_method_name is True
        assert args.infer_variable_name is False

    def test_strtobool_flags(self):
        parser = build_parser()
        args = parser.parse_args(
            ["--infer_method_name", "False", "--infer_variable_name", "true"])
        assert args.infer_method_name is False
        assert args.infer_variable_name is True
        with pytest.raises(SystemExit):
            parser.parse_args(["--infer_method_name", "maybe"])

    def test_config_mapping(self):
        args = build_parser().parse_args([
            "--encode_size", "64", "--lr", "0.005",
            "--angular_margin_loss", "--compute_dtype", "bfloat16",
            "--data_axis", "4",
        ])
        config = config_from_args(args)
        assert config.encode_size == 64
        assert config.lr == 0.005
        assert config.angular_margin_loss is True
        assert config.compute_dtype == "bfloat16"
        assert config.data_axis == 4


class TestSinks:
    def test_floyd_sink_emits_json_lines(self, capsys):
        floyd_sink(3, {"train_loss": 1.5, "f1": 0.25})
        lines = [json.loads(l) for l in capsys.readouterr().out.splitlines()]
        assert {"metric": "train_loss", "value": 1.5} in lines
        assert {"metric": "f1", "value": 0.25} in lines

    def test_sink_selection(self):
        args = build_parser().parse_args([])
        assert len(sinks_from_args(args)) == 1
        args = build_parser().parse_args(["--env", "floyd"])
        assert floyd_sink in sinks_from_args(args)

    def test_tensorboard_sink_writes_events(self, tmp_path):
        pytest.importorskip("tensorboardX")
        args = build_parser().parse_args(
            ["--env", "tensorboard", "--tensorboard_dir", str(tmp_path)])
        sinks = sinks_from_args(args)
        sinks[-1](0, {"f1": 0.5})
        assert any(f.startswith("events") for f in os.listdir(tmp_path))


class TestEndToEnd:
    def test_main_trains_and_writes_artifacts(self, corpus_files, tmp_path):
        out = tmp_path / "out"
        main([
            "--corpus_path", corpus_files["corpus"],
            "--path_idx_path", corpus_files["path_idx"],
            "--terminal_idx_path", corpus_files["terminal_idx"],
            "--model_path", str(out),
            "--vectors_path", str(out / "code.vec"),
            "--max_epoch", "2",
            "--encode_size", "32",
            "--terminal_embed_size", "16",
            "--path_embed_size", "16",
            "--max_path_length", "16",
            "--batch_size", "32",
            "--print_sample_cycle", "0",
        ])
        assert (out / "code.vec").exists()

    def test_main_hpo_path(self, corpus_files, tmp_path, monkeypatch):
        # wire-up only: 1 trial, 1 epoch; shrink the sampled space
        import code2vec_tpu.hpo as hpo_mod

        monkeypatch.setattr(
            hpo_mod, "sample_train_config",
            lambda trial, cfg: cfg.with_updates(
                encode_size=trial.suggest_int("encode_size", 8, 16, log=True)),
        )
        main([
            "--corpus_path", corpus_files["corpus"],
            "--path_idx_path", corpus_files["path_idx"],
            "--terminal_idx_path", corpus_files["terminal_idx"],
            "--find_hyperparams", "--num_trials", "1",
            "--max_epoch", "1",
            "--terminal_embed_size", "8", "--path_embed_size", "8",
            "--max_path_length", "8", "--batch_size", "16",
        ])
