"""Fleet serving (code2vec_tpu.serve.fleet) + live hot-swap (serve/swap.py).

The load-bearing contracts pinned here:

- the swap controller builds/validates a shadow generation on a
  background thread, commits it atomically, keeps the old generation
  RESIDENT, and ``rollback`` restores the prior version's
  bitwise-identical outputs (same executables, nothing rebuilt);
- a failed build or failed golden validation NEVER touches the active
  pointer;
- the router places requests on the least-loaded healthy replica, sheds
  per-SLO-class on budget exhaustion and deadline expiry (tiered — never
  one global max_pending), retries requests stranded on a dead replica,
  and evicts/respawns replicas that miss health probes;
- a real 2-replica fleet of subprocess workers performs one ROLLING
  hot-swap under a trickle of requests with zero failed requests and
  zero post-warmup recompiles (the CI fleet-smoke scenario).
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import threading
import time
from concurrent.futures import Future

import numpy as np
import pytest

import jax

from code2vec_tpu.obs.runtime import RuntimeHealth
from code2vec_tpu.serve.batcher import MicroBatcher
from code2vec_tpu.serve.engine import ServingEngine
from code2vec_tpu.serve.fleet.replica import ReplicaDied
from code2vec_tpu.serve.fleet.router import FleetRouter
from code2vec_tpu.serve.fleet.slo import (
    DEFAULT_SLO,
    SloClass,
    classify_op,
    parse_slo_spec,
)
from code2vec_tpu.serve.swap import (
    Generation,
    GoldenSet,
    SwapController,
    SwapValidationError,
    validate_generation,
)

pytestmark = pytest.mark.fleet

BAG = 16
LADDER = (4, 8, 16)
BATCH_SIZES = (1, 4)
N_TERMINALS, N_PATHS, N_LABELS = 50, 40, 6


# ---------------------------------------------------------------------------
# SLO classes
# ---------------------------------------------------------------------------


def test_classify_ops():
    assert classify_op("predict") == "embed"
    assert classify_op("embed") == "embed"
    assert classify_op("neighbors") == "neighbors"
    for op in ("health", "swap_status", "reload", "rollback", "shutdown"):
        assert classify_op(op) == "health"
    assert classify_op("nope") is None
    assert classify_op(None) is None


def test_parse_slo_spec_overrides_defaults():
    classes = parse_slo_spec("embed=512:1500, neighbors=8:9000")
    assert classes["embed"].budget == 512
    assert classes["embed"].deadline_ms == 1500.0
    assert classes["neighbors"].budget == 8
    assert classes["health"] == DEFAULT_SLO["health"]  # untouched


def test_parse_slo_spec_rejects_garbage():
    with pytest.raises(ValueError, match="unknown SLO class"):
        parse_slo_spec("turbo=1:1")
    with pytest.raises(ValueError, match="expected"):
        parse_slo_spec("embed=12")
    with pytest.raises(ValueError, match="budget"):
        SloClass("embed", budget=0, deadline_ms=1.0)


# ---------------------------------------------------------------------------
# router against in-process fake replicas (no jax, no subprocesses)
# ---------------------------------------------------------------------------


class FakeReplica:
    """In-process stand-in for ReplicaHandle: resolves each request on a
    worker thread after ``latency_s``; scriptable behavior + death."""

    def __init__(self, slot, incarnation=0, latency_s=0.0, behavior=None):
        self.slot = slot
        self.incarnation = incarnation
        self.latency_s = latency_s
        self.behavior = behavior or (
            lambda req: {"ok": True, "op": req.get("op"), "slot": self.slot}
        )
        self._alive = True
        self._inflight = 0
        self._lock = threading.Lock()
        self.probe_failures = 0
        self.last_health = None
        self.death_reason = None
        self.pid = 40000 + slot
        self.sent: list[dict] = []

    @property
    def alive(self):
        return self._alive

    @property
    def in_flight(self):
        return self._inflight

    def send(self, request):
        if not self._alive:
            raise ReplicaDied(f"fake r{self.slot} dead")
        self.sent.append(dict(request))
        future: Future = Future()
        with self._lock:
            self._inflight += 1

        def run():
            if self.latency_s:
                time.sleep(self.latency_s)
            with self._lock:
                self._inflight -= 1
            if not self._alive:
                future.set_exception(ReplicaDied(f"fake r{self.slot} died"))
                return
            try:
                future.set_result(self.behavior(request))
            except Exception as exc:  # noqa: BLE001 - scripted failure
                future.set_exception(exc)

        threading.Thread(target=run, daemon=True).start()
        return future

    def wait_ready(self, timeout):
        return {"ok": True}

    def stop(self, timeout=10.0):
        self._alive = False

    def kill(self, timeout=10.0):
        self._alive = False
        self.death_reason = "killed"

    def die(self):
        self._alive = False
        self.death_reason = "scripted death"


def make_router(replicas, **kw):
    kw.setdefault("health", RuntimeHealth())
    kw.setdefault("probe_interval_s", 60.0)  # probing off unless asked
    spawned = []

    def factory(slot, incarnation):
        if callable(replicas):
            handle = replicas(slot, incarnation)
        else:
            handle = replicas[slot]
        spawned.append(handle)
        return handle

    n = kw.pop("n_replicas", None) or (
        2 if callable(replicas) else len(replicas)
    )
    router = FleetRouter(factory, n, **kw)
    router._spawned_for_test = spawned
    return router


def test_router_routes_across_replicas_least_loaded():
    fakes = [FakeReplica(0, latency_s=0.02), FakeReplica(1, latency_s=0.02)]
    router = make_router(fakes)
    try:
        resolvers = [
            router.handle_async({"op": "embed", "source": "x", "id": i})
            for i in range(12)
        ]
        payloads = [r() for r in resolvers]
        assert all(p["ok"] for p in payloads)
        assert [p["id"] for p in payloads] == list(range(12))
        # least-loaded placement spreads work over both replicas
        assert all(len(f.sent) > 0 for f in fakes)
        snap = router.health.snapshot()
        assert snap["counters"]["slo.embed.completed"] == 12
        assert snap["latencies_ms"]["slo.embed.e2e_ms"]["count"] == 12
    finally:
        router.close()


def test_router_budget_shed_is_per_class():
    # one replica, in-flight cap 1, slow: the embed queue (budget 2)
    # fills while neighbors (budget 4) still admits — tiered shedding
    slo = {
        "health": DEFAULT_SLO["health"],
        "embed": SloClass("embed", budget=2, deadline_ms=10_000.0),
        "neighbors": SloClass("neighbors", budget=4, deadline_ms=10_000.0),
    }
    fake = FakeReplica(0, latency_s=0.2)
    router = make_router([fake], slo=slo, per_replica_inflight=1)
    try:
        resolvers = [
            router.handle_async({"op": "embed", "source": "x"})
            for i in range(8)
        ]
        payloads = [r() for r in resolvers]
        shed = [p for p in payloads if p.get("error_kind") == "overloaded"]
        served = [p for p in payloads if p.get("ok")]
        assert shed and served
        assert all(p["slo_class"] == "embed" for p in shed)
        # the neighbors tier still admits while embed sheds
        assert router.handle({"op": "neighbors", "vector": [1.0]})["ok"]
        counters = router.health.snapshot()["counters"]
        assert counters["slo.embed.shed_budget"] == len(shed)
    finally:
        router.close()


def test_router_deadline_shed():
    slo = {
        "health": DEFAULT_SLO["health"],
        "embed": SloClass("embed", budget=64, deadline_ms=80.0),
        "neighbors": DEFAULT_SLO["neighbors"],
    }
    fake = FakeReplica(0, latency_s=0.3)
    router = make_router([fake], slo=slo, per_replica_inflight=1)
    try:
        resolvers = [
            router.handle_async({"op": "embed", "source": "x"})
            for i in range(4)
        ]
        payloads = [r() for r in resolvers]
        kinds = [p.get("error_kind") for p in payloads]
        # the first dispatches; later ones age out waiting for the one
        # in-flight slot and are shed as expired, not served late
        assert payloads[0].get("ok")
        assert "deadline" in kinds
        counters = router.health.snapshot()["counters"]
        assert counters["slo.embed.shed_deadline"] >= 1
    finally:
        router.close()


def test_router_retries_requests_stranded_on_dead_replica():
    sick = FakeReplica(0, latency_s=0.05)
    healthy = FakeReplica(1)

    real_send = FakeReplica.send

    def dying_send(self, request):
        future = real_send(self, request)
        self.die()  # dies with the request in flight
        return future

    sick.send = dying_send.__get__(sick)
    router = make_router([sick, healthy])
    try:
        payload = router.handle({"op": "embed", "source": "x"})
        assert payload["ok"] and payload["slot"] == 1
        assert router.health.snapshot()["counters"]["fleet.retries"] >= 1
    finally:
        router.close()


def test_router_evicts_and_respawns_on_probe_failure():
    incarnations = []

    def factory(slot, incarnation):
        incarnations.append((slot, incarnation))
        return FakeReplica(slot, incarnation=incarnation)

    router = make_router(factory, n_replicas=2, probe_interval_s=0.05,
                         probe_timeout_s=0.5, max_probe_failures=1)
    try:
        router._spawned_for_test[0].die()
        deadline = time.time() + 10.0
        while time.time() < deadline:
            if (0, 1) in incarnations:
                break
            time.sleep(0.05)
        assert (0, 1) in incarnations, "dead replica was not respawned"
        counters = router.health.snapshot()["counters"]
        assert counters["fleet.evictions"] >= 1
        assert counters["fleet.respawns"] >= 1
        # the respawned slot serves again
        assert router.handle({"op": "embed", "source": "x"})["ok"]
        health = router.handle({"op": "health"})
        assert health["ok"]
        assert all(r["alive"] for r in health["fleet"]["replicas"])
    finally:
        router.close()


def test_router_unknown_op_and_closed():
    router = make_router([FakeReplica(0)])
    assert router.handle({"op": "nope"})["error_kind"] == "bad_request"
    router.close()
    assert router.handle({"op": "embed", "source": "x"})[
        "error_kind"
    ] == "closed"


def _swappable_fake(slot, incarnation=0, poll_count=2):
    """A fake replica implementing the worker's swap state machine:
    ``reload`` answers ok, then ``swap_status`` reports building for
    ``poll_count`` polls before committing."""
    state = {"version": "v0#g0", "building": 0}

    def behavior(req):
        op = req.get("op")
        if op == "reload":
            state["building"] = poll_count
            state["target"] = req.get("model_path")
            return {"ok": True, "swap": {"state": "building"}}
        if op == "swap_status":
            if state["building"] > 0:
                state["building"] -= 1
                return {"ok": True, "swap": {"state": "building"}}
            if state.get("target"):
                state["version"] = f"{state.pop('target')}#g1"
            return {
                "ok": True,
                "swap": {
                    "state": "idle",
                    "active_version": state["version"],
                    "last_swap": {
                        "outcome": "committed",
                        "version": state["version"],
                        "build_ms": 1.0,
                        "validate_ms": 1.0,
                    },
                },
            }
        if op == "rollback":
            state["version"] = "v0#g0"
            return {"ok": True,
                    "swap": {"state": "idle",
                             "active_version": state["version"]}}
        return {"ok": True, "op": op, "slot": slot}

    return FakeReplica(slot, incarnation=incarnation, behavior=behavior)


def test_router_rolling_swap_walks_replicas_serially_then_rolls_back():
    fakes = [_swappable_fake(0), _swappable_fake(1)]
    router = make_router(fakes, swap_timeout_s=30.0)
    try:
        payload = router.handle(
            {"op": "reload", "model_path": "v1", "wait": True}
        )
        assert payload["ok"], payload
        rolling = payload["rolling"]
        assert rolling["outcome"] == "committed"
        assert [r["slot"] for r in rolling["replicas"]] == [0, 1]
        assert all(
            r["outcome"] == "committed" and r["version"] == "v1#g1"
            for r in rolling["replicas"]
        )
        # serial walk: replica 1's reload only after replica 0 committed
        r0_done = [i for i, q in enumerate(fakes[0].sent)
                   if q["op"] == "swap_status"]
        r1_reload = [i for i, q in enumerate(fakes[1].sent)
                     if q["op"] == "reload"]
        assert r0_done and r1_reload
        status = router.handle({"op": "swap_status"})
        assert status["rolling"]["outcome"] == "committed"
        back = router.handle({"op": "rollback"})
        assert back["ok"]
        assert all(r["outcome"] == "rolled_back" for r in back["replicas"])
    finally:
        router.close()


def test_router_rolling_swap_failure_aborts_roll():
    def failing_behavior(req):
        if req.get("op") == "reload":
            return {"ok": True, "swap": {"state": "building"}}
        if req.get("op") == "swap_status":
            return {"ok": True, "swap": {
                "state": "idle",
                "last_swap": {"outcome": "failed",
                              "error": "validation miss"},
            }}
        return {"ok": True}

    fakes = [FakeReplica(0, behavior=failing_behavior), _swappable_fake(1)]
    router = make_router(fakes)
    try:
        payload = router.handle(
            {"op": "reload", "model_path": "v1", "wait": True}
        )
        assert not payload["ok"]
        assert payload["error_kind"] == "swap_failed"
        assert "validation miss" in payload["error"]
        # the roll stopped at replica 0: replica 1 was never asked
        assert not [q for q in fakes[1].sent if q["op"] == "reload"]
    finally:
        router.close()


# ---------------------------------------------------------------------------
# SwapController against real engines (tiny model, CPU)
# ---------------------------------------------------------------------------


def make_state(seed: int):
    from code2vec_tpu.models.code2vec import Code2VecConfig
    from code2vec_tpu.train.config import TrainConfig
    from code2vec_tpu.train.step import create_train_state

    cfg = TrainConfig(batch_size=4, max_path_length=BAG)
    mc = Code2VecConfig(
        terminal_count=N_TERMINALS, path_count=N_PATHS, label_count=N_LABELS,
        terminal_embed_size=8, path_embed_size=8, encode_size=12,
        dropout_prob=0.0,
    )
    example = {
        "starts": np.zeros((1, BAG), np.int32),
        "paths": np.zeros((1, BAG), np.int32),
        "ends": np.zeros((1, BAG), np.int32),
        "labels": np.zeros(1, np.int32),
        "example_mask": np.ones(1, np.float32),
    }
    return create_train_state(cfg, mc, jax.random.PRNGKey(seed), example)


def make_generation(seed: int, version: str, health=None) -> Generation:
    health = health or RuntimeHealth()
    engine = ServingEngine(
        make_state(seed), max_width=BAG, model_dims=(8, 8, 12),
        ladder=LADDER, batch_sizes=BATCH_SIZES, health=health,
        version=version,
    )
    engine.prepare()
    batcher = MicroBatcher(engine, deadline_ms=1.0, health=health)
    return Generation(version=version, engine=engine, batcher=batcher)


GOLDEN = GoldenSet(n_terminals=N_TERMINALS, n_paths=N_PATHS)


def one_request(width=7, seed=3):
    rng = np.random.default_rng(seed)
    return np.stack(
        [
            rng.integers(1, N_TERMINALS, width),
            rng.integers(1, N_PATHS, width),
            rng.integers(1, N_TERMINALS, width),
        ],
        axis=1,
    ).astype(np.int32)


def test_swap_commit_then_rollback_restores_bitwise():
    health = RuntimeHealth()
    controller = SwapController(
        make_generation(0, "v0", health),
        build=lambda target: make_generation(1, str(target), health),
        golden=GOLDEN, health=health,
    )
    try:
        req = one_request()
        before = controller.active.batcher.submit(req).result(60)

        status = controller.reload("v1", wait=True)
        assert status["state"] == "idle"
        last = status["last_swap"]
        assert last["outcome"] == "committed", last
        assert last["golden_requests"] == len(GOLDEN.requests_for(
            controller.active
        ))
        assert controller.active.version == "v1"
        assert controller.previous is not None
        assert controller.previous.version == "v0"

        after = controller.active.batcher.submit(req).result(60)
        # different weights: the new generation really serves
        assert not np.array_equal(before.code_vector, after.code_vector)

        rolled = controller.rollback()
        assert rolled["active_version"] == "v0"
        restored = controller.active.batcher.submit(req).result(60)
        # the old generation was resident the whole time — same
        # executables, same tables: BITWISE identical, first request
        assert np.array_equal(before.code_vector, restored.code_vector)
        assert np.array_equal(before.logits, restored.logits)
        # and zero post-warmup compiles anywhere
        assert controller.active.engine.post_warmup_compiles == 0
        assert controller.previous.engine.post_warmup_compiles == 0
    finally:
        controller.close()


def test_swap_failure_keeps_active_untouched():
    health = RuntimeHealth()

    def exploding_build(target):
        raise RuntimeError("checkpoint is corrupt")

    controller = SwapController(
        make_generation(0, "v0", health), build=exploding_build,
        golden=GOLDEN, health=health,
    )
    try:
        status = controller.reload("v1", wait=True)
        assert status["state"] == "idle"
        assert status["last_swap"]["outcome"] == "failed"
        assert "checkpoint is corrupt" in status["last_swap"]["error"]
        assert controller.active.version == "v0"
        assert controller.previous is None
        # still serving
        result = controller.active.batcher.submit(one_request()).result(60)
        assert np.isfinite(result.code_vector).all()
        # nothing to roll back to
        with pytest.raises(ValueError, match="no previous generation"):
            controller.rollback()
    finally:
        controller.close()


def test_swap_validation_recall_floor_blocks_commit():
    from code2vec_tpu.serve.retrieval import RetrievalIndex

    health = RuntimeHealth()
    rng = np.random.default_rng(0)
    index = RetrievalIndex(
        [f"m{i}" for i in range(20)],
        rng.normal(size=(20, 12)).astype(np.float32),
    )

    def build(target):
        gen = make_generation(1, str(target), health)
        gen.retrieval = index
        return gen

    impossible = GoldenSet(
        n_terminals=N_TERMINALS, n_paths=N_PATHS, min_recall=1.01
    )
    controller = SwapController(
        make_generation(0, "v0", health), build=build, golden=impossible,
        health=health,
    )
    try:
        status = controller.reload("v1", wait=True)
        assert status["last_swap"]["outcome"] == "failed"
        assert "recall" in status["last_swap"]["error"]
        assert controller.active.version == "v0"
    finally:
        controller.close()
    # and directly: the exact backend passes any achievable floor
    gen = build("direct")
    try:
        report = validate_generation(
            gen, GoldenSet(n_terminals=N_TERMINALS, n_paths=N_PATHS,
                           min_recall=0.99)
        )
        assert report["recall"] == 1.0
    finally:
        gen.close()


def test_concurrent_swap_rejected_while_busy():
    health = RuntimeHealth()
    release = threading.Event()

    def slow_build(target):
        release.wait(30)
        return make_generation(1, str(target), health)

    controller = SwapController(
        make_generation(0, "v0", health), build=slow_build, golden=GOLDEN,
        health=health,
    )
    try:
        controller.reload("v1", wait=False)
        with pytest.raises(ValueError, match="already in progress"):
            controller.reload("v2")
        with pytest.raises(ValueError, match="in progress"):
            controller.rollback()
    finally:
        release.set()
        controller.wait(60)
        controller.close()


def test_codeserver_swap_ops_and_health_block():
    from code2vec_tpu.serve.protocol import CodeServer

    health = RuntimeHealth()
    gen0 = make_generation(0, "v0", health)
    server = CodeServer(
        None, gen0.engine, gen0.batcher, health=health, version="v0",
        factory=lambda target: make_generation(1, str(target), health),
        golden=GOLDEN,
    )
    try:
        status = server.handle({"op": "swap_status"})
        assert status["ok"] and status["swap"]["state"] == "idle"
        reloaded = server.handle(
            {"op": "reload", "model_path": "v1", "wait": True}
        )
        assert reloaded["ok"], reloaded
        assert reloaded["swap"]["active_version"] == "v1"
        health_payload = server.handle({"op": "health"})
        assert health_payload["version"] == "v1"
        assert health_payload["swap"]["previous_version"] == "v0"
        back = server.handle({"op": "rollback", "id": 7})
        assert back["ok"] and back["id"] == 7
        assert back["swap"]["active_version"] == "v0"
        # per-op metrics follow the one schema
        snap = health.snapshot()
        assert snap["counters"]["serve.op.reload.requests"] == 1
        assert snap["latencies_ms"]["serve.op.rollback.e2e_ms"]["count"] == 1
        # rollback again: previous is v1 now
        assert server.handle({"op": "rollback"})["swap"][
            "active_version"
        ] == "v1"
    finally:
        server.close()


def test_codeserver_without_factory_rejects_reload():
    health = RuntimeHealth()
    gen0 = make_generation(0, "v0", health)
    from code2vec_tpu.serve.protocol import CodeServer

    server = CodeServer(None, gen0.engine, gen0.batcher, health=health)
    try:
        resp = server.handle({"op": "reload", "model_path": "x"})
        assert resp["error_kind"] == "bad_request"
        assert "factory" in resp["error"]
    finally:
        server.close()


# ---------------------------------------------------------------------------
# bench --serve --rolling-swap: the acceptance harness
# ---------------------------------------------------------------------------


def test_bench_rolling_swap_arm_zero_failures_bounded_p99():
    bench_path = os.path.join(os.path.dirname(__file__), "..", "bench.py")
    env = dict(
        os.environ,
        JAX_PLATFORMS="cpu",
        BENCH_SUPERVISED="1",
        BENCH_SERVE_REQUESTS="150",
        BENCH_SERVE_QPS="150",
        BENCH_BAG="16",
        BENCH_EMBED="8",
        BENCH_ENCODE="12",
        BENCH_SERVE_TERMINALS="200",
        BENCH_SERVE_PATHS="150",
        BENCH_SERVE_LABELS="20",
        # CI boxes are noisy; the bound under test is the mechanism, the
        # 3x default stands for the real acceptance run
        BENCH_SWAP_P99_FACTOR="6.0",
    )
    proc = subprocess.run(
        [sys.executable, bench_path, "--serve", "--rolling-swap"],
        capture_output=True, text=True, env=env, timeout=600,
        cwd=os.path.dirname(bench_path),
    )
    assert proc.returncode == 0, proc.stderr[-4000:]
    metric = json.loads(proc.stdout.strip().splitlines()[-1])
    swap = metric["rolling_swap"]
    assert swap["outcome"] == "committed"
    assert swap["failed_requests"] == 0
    assert swap["rollback_bitwise"] is True
    assert swap["p99_ratio"] is not None
    detail_line = next(
        l for l in proc.stderr.splitlines() if l.startswith('{"detail"')
    )
    detail = json.loads(detail_line)["detail"]["rolling_swap"]
    assert detail["versions_differ"] is True
    assert detail["post_warmup_recompiles_shadow"] == 0
    assert detail["golden_requests"] > 0
    assert detail["requests_in_swap_window"] > 0


# ---------------------------------------------------------------------------
# real 2-replica fleet e2e: the CI fleet-smoke scenario
# ---------------------------------------------------------------------------

PY = """
def add(a, b):
    total = a + b
    return total


def mul(a, b):
    product = a * b
    return product
"""


@pytest.fixture(scope="module")
def trained_tiny(tmp_path_factory):
    from code2vec_tpu.data.reader import load_corpus
    from code2vec_tpu.export import export_from_checkpoint
    from code2vec_tpu.pyextract import extract_python_dataset
    from code2vec_tpu.train.config import TrainConfig
    from code2vec_tpu.train.loop import train

    root = tmp_path_factory.mktemp("fleet_py")
    src, ds, out = root / "src", root / "ds", root / "out"
    for d in (src, ds, out):
        d.mkdir()
    (src / "util.py").write_text(PY)
    extract_python_dataset(str(ds), str(src), [("util.py", "*")])
    data = load_corpus(
        ds / "corpus.txt", ds / "path_idxs.txt", ds / "terminal_idxs.txt"
    )
    cfg = TrainConfig(
        max_epoch=4, batch_size=2, encode_size=16, terminal_embed_size=8,
        path_embed_size=8, max_path_length=32, lr=0.01, print_sample_cycle=0,
    )
    train(cfg, data, out_dir=str(out))
    export_from_checkpoint(cfg, data, str(out), str(out / "code.vec"))
    return ds, out


@pytest.mark.usefixtures("zero_leaked_handles")
def test_fleet_two_replicas_rolling_swap_under_trickle(trained_tiny):
    """Boot a REAL 2-replica fleet (subprocess workers), keep a trickle of
    requests flowing, perform one rolling hot-swap and a rollback, and
    assert ZERO failed requests and ZERO post-warmup recompiles."""
    from code2vec_tpu.serve.fleet.__main__ import build_parser, build_router

    ds, out = trained_tiny
    args = build_parser().parse_args([
        "--replicas", "2",
        "--model_path", str(out),
        "--terminal_idx_path", str(ds / "terminal_idxs.txt"),
        "--path_idx_path", str(ds / "path_idxs.txt"),
        "--deadline_ms", "2",
        "--probe_interval_s", "0.5",
        "--boot_timeout_s", "600",
    ])
    router, events = build_router(args)
    failures: list = []
    responses: list = []
    stop = threading.Event()

    def trickle():
        while not stop.is_set():
            payload = router.handle({
                "op": "embed", "source": PY, "language": "python",
                "method_name": "add",
            })
            responses.append(payload)
            if payload.get("error"):
                failures.append(payload)
                return
            time.sleep(0.05)

    thread = threading.Thread(target=trickle, daemon=True)
    thread.start()
    try:
        # a few steady-state requests first
        time.sleep(1.0)
        rolled = router.handle(
            {"op": "reload", "model_path": str(out), "wait": True}
        )
        assert rolled["ok"], rolled
        assert rolled["rolling"]["outcome"] == "committed"
        assert len(rolled["rolling"]["replicas"]) == 2
        # keep the trickle flowing on the new version, then roll back
        time.sleep(1.0)
        back = router.handle({"op": "rollback"})
        assert back["ok"], back
        time.sleep(0.5)
    finally:
        stop.set()
        thread.join(30)
    try:
        assert not failures, failures[:3]
        assert len(responses) >= 10
        # neighbors flows through the fleet too (code.vec was exported)
        neighbors = router.handle({
            "op": "neighbors", "source": PY, "language": "python",
            "method_name": "add", "top_k": 2,
        })
        assert neighbors["ok"], neighbors
        status = router.handle({"op": "swap_status"})
        assert status["rolling"]["outcome"] == "committed"
        for replica in status["replicas"]:
            swap = replica["swap"]
            assert swap["state"] == "idle"
            # after rollback the ORIGINAL generation is active again and
            # the swapped-in one stays resident
            assert swap["active_version"].endswith("#g0")
            assert swap["previous_version"].endswith("#g1")
        health = router.handle({"op": "health"})
        assert health["ok"], health
        for replica in health["fleet"]["replicas"]:
            assert replica["alive"]
            assert replica["post_warmup_compiles"] == 0
    finally:
        router.close()
        if events is not None:
            events.close()


@pytest.mark.sync
@pytest.mark.usefixtures("zero_leaked_handles")
def test_fleet_rolling_swap_with_lock_sanitizer(trained_tiny, monkeypatch):
    """The sanitizer-on acceptance run: a REAL 2-replica fleet with the
    lock sanitizer enabled in the router AND (via inherited env) both
    subprocess workers, one rolling hot-swap under a request trickle —
    ZERO lock-order violations anywhere, zero failed requests, zero
    post-warmup recompiles."""
    from code2vec_tpu.obs import sync as syncmod
    from code2vec_tpu.serve.fleet.__main__ import build_parser, build_router

    monkeypatch.setenv(syncmod.SYNC_DEBUG_ENV, "1")
    syncmod.reset_sync_state()
    ds, out = trained_tiny
    args = build_parser().parse_args([
        "--replicas", "2",
        "--model_path", str(out),
        "--terminal_idx_path", str(ds / "terminal_idxs.txt"),
        "--path_idx_path", str(ds / "path_idxs.txt"),
        "--deadline_ms", "2",
        "--probe_interval_s", "0.5",
        "--boot_timeout_s", "600",
        "--sync_debug",
    ])
    router, events = build_router(args)
    failures: list = []
    stop = threading.Event()

    def trickle():
        while not stop.is_set():
            payload = router.handle({
                "op": "embed", "source": PY, "language": "python",
                "method_name": "add",
            })
            if payload.get("error"):
                failures.append(payload)
                return
            time.sleep(0.05)

    thread = threading.Thread(target=trickle, daemon=True)
    thread.start()
    try:
        time.sleep(0.5)
        rolled = router.handle(
            {"op": "reload", "model_path": str(out), "wait": True}
        )
        assert rolled["ok"], rolled
        assert rolled["rolling"]["outcome"] == "committed"
        time.sleep(0.5)
    finally:
        stop.set()
        thread.join(30)
    try:
        assert not failures, failures[:3]
        # router-side: the traced router/cache/SLO locks saw no inversion
        assert syncmod.violations() == []
        snap = syncmod.sync_snapshot()
        assert snap["enabled"] and snap["order_violations"] == 0
        # worker-side: each replica's health payload carries its own
        # sanitizer block — enabled, zero violations, zero recompiles
        health = router.handle({"op": "health"})
        assert health["ok"], health
        # the router's own snapshot rides the fleet block too, so an
        # operator sees both sides from one /healthz scrape
        assert health["fleet"]["sync"]["enabled"] is True
        assert health["fleet"]["sync"]["order_violations"] == 0
        for replica in health["fleet"]["replicas"]:
            assert replica["alive"]
            assert replica["post_warmup_compiles"] == 0
            worker_sync = replica["sync"]
            assert worker_sync["enabled"] is True
            assert worker_sync["order_violations"] == 0
    finally:
        router.close()
        if events is not None:
            events.close()
        syncmod.reset_sync_state()


@pytest.mark.lifecycle
def test_fleet_rolling_swap_zero_leaked_handles(
    trained_tiny, monkeypatch, tmp_path
):
    """The ledger-on acceptance run: a REAL 2-replica fleet with the
    handle ledger enabled in the router AND (via the forwarded flag)
    both subprocess workers, one rolling hot-swap + rollback under a
    request trickle — ZERO leaked handles anywhere (router ledger drains
    to empty, no worker emits a ``handle_leak`` shutdown event), zero
    failed requests, zero post-warmup recompiles."""
    from code2vec_tpu.obs import handles as handlesmod
    from code2vec_tpu.serve.fleet.__main__ import build_parser, build_router

    monkeypatch.setenv(handlesmod.HANDLE_DEBUG_ENV, "1")
    handlesmod.reset_handle_state()
    ds, out = trained_tiny
    events_dir = tmp_path / "events"
    args = build_parser().parse_args([
        "--replicas", "2",
        "--model_path", str(out),
        "--terminal_idx_path", str(ds / "terminal_idxs.txt"),
        "--path_idx_path", str(ds / "path_idxs.txt"),
        "--deadline_ms", "2",
        "--probe_interval_s", "0.5",
        "--boot_timeout_s", "600",
        "--events_dir", str(events_dir),
        "--handle_debug",
    ])
    before = {r["token"] for r in handlesmod.open_handles()}
    router, events = build_router(args)
    failures: list = []
    stop = threading.Event()

    def trickle():
        while not stop.is_set():
            payload = router.handle({
                "op": "embed", "source": PY, "language": "python",
                "method_name": "add",
            })
            if payload.get("error"):
                failures.append(payload)
                return
            time.sleep(0.05)

    thread = threading.Thread(target=trickle, daemon=True)
    thread.start()
    try:
        time.sleep(0.5)
        rolled = router.handle(
            {"op": "reload", "model_path": str(out), "wait": True}
        )
        assert rolled["ok"], rolled
        assert rolled["rolling"]["outcome"] == "committed"
        time.sleep(0.5)
        back = router.handle({"op": "rollback"})
        assert back["ok"], back
        time.sleep(0.3)
    finally:
        stop.set()
        thread.join(30)
    try:
        assert not failures, failures[:3]
        # mid-flight visibility: the router ledger sees its own handles
        # and each replica's health payload carries its worker-side block
        health = router.handle({"op": "health"})
        assert health["ok"], health
        fleet_handles = health["fleet"]["handles"]
        assert fleet_handles["enabled"] is True
        assert fleet_handles["open"].get("replica") == 2
        for replica in health["fleet"]["replicas"]:
            assert replica["alive"]
            assert replica["post_warmup_compiles"] == 0
            worker_handles = replica["handles"]
            assert worker_handles["enabled"] is True
            assert worker_handles["leaked"] == 0
            # the worker owns at least its batcher + active generation
            assert worker_handles["open_total"] >= 2
    finally:
        router.close()
        if events is not None:
            events.close()
    try:
        # router-side: everything opened since `before` was closed again
        open_now = {r["token"] for r in handlesmod.open_handles()}
        assert open_now <= before, handlesmod.open_handles()
        # worker-side: each replica ran its serve.shutdown leak report
        # into its own event log on the graceful stop — no handle_leak
        # event anywhere means both workers drained their ledgers too
        leak_lines = []
        for log_path in events_dir.rglob("events-*.jsonl"):
            for line in log_path.read_text().splitlines():
                if '"handle_leak"' in line:
                    leak_lines.append((log_path.name, line))
        assert leak_lines == []
    finally:
        handlesmod.reset_handle_state()
