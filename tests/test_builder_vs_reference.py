"""Differential test: our epoch builders vs the REFERENCE's DatasetBuilder.

`data/pipeline.py` mirrors the reference's per-epoch tensor construction
(model/dataset_builder.py:112-210): @question substitution, per-method
context subsampling, and the variable-task expansion (one example per
@var alias, target renamed to @question). Both sides shuffle with their
own RNGs, so rows are compared as SORTED context triples (order within a
bag is irrelevant to the permutation-invariant attention pooling), and
the subsample case (n > L) is checked against its invariants
(every row a without-replacement subset of the item's contexts).

The reference's `build_data` is invoked directly on an
``object.__new__``-constructed builder (its ``__init__`` only does the
unseeded train/test split and logging, neither of which is under test).
"""

from collections import Counter

import numpy as np
import pytest

from conftest import import_reference, make_reference_corpus

_builder_mod = import_reference("model.dataset_builder")
ReferenceReader = import_reference("model.dataset_reader").DatasetReader

from code2vec_tpu.data.pipeline import (  # noqa: E402
    build_method_epoch,
    build_variable_epoch,
)
from code2vec_tpu.data.reader import load_corpus  # noqa: E402

L = 20  # max_path_length for all tests


def _reference_build(reader, items, max_path_length):
    b = object.__new__(_builder_mod.DatasetBuilder)
    b.reader = reader
    ids, starts, paths, ends, labels = b.build_data(reader, items, max_path_length)
    return (
        ids,
        starts.numpy(),
        paths.numpy(),
        ends.numpy(),
        labels.numpy(),
    )


def _row_triples(starts, paths, ends):
    """Sorted (start, path, end) triples of one row, pads (path==0) dropped."""
    keep = paths != 0
    return sorted(zip(starts[keep].tolist(), paths[keep].tolist(), ends[keep].tolist()))


def _make_corpus(tmp_path, rng, **kwargs):
    """Unique label per method and per (method, alias) so rows can be keyed."""
    kwargs.setdefault("n_methods", 18)
    kwargs.setdefault("n_terminals", 26)
    kwargs.setdefault("n_paths", 30)
    kwargs.setdefault("n_vars", 4)
    return make_reference_corpus(
        tmp_path, rng, include_method_token=True, **kwargs
    )


def _load_both(corpus, path_idx, terminal_idx, infer_method, infer_variable):
    theirs_reader = ReferenceReader(
        str(corpus), str(path_idx), str(terminal_idx),
        infer_method=infer_method, infer_variable=infer_variable,
        shuffle_variable_indexes=False,
    )
    ours = load_corpus(
        corpus, path_idx, terminal_idx,
        infer_method=infer_method, infer_variable=infer_variable,
        cache=False,
    )
    return theirs_reader, ours


@pytest.mark.parametrize("seed", [0, 1])
def test_method_epoch_matches_reference(tmp_path, seed):
    rng = np.random.default_rng(seed)
    corpus, path_idx, terminal_idx = _make_corpus(tmp_path, rng)
    theirs_reader, ours = _load_both(corpus, path_idx, terminal_idx, True, False)

    ids_t, starts_t, paths_t, ends_t, labels_t = _reference_build(
        theirs_reader, theirs_reader.items, L
    )
    epoch = build_method_epoch(
        ours, np.arange(ours.n_items), L, np.random.default_rng(seed + 100)
    )

    assert epoch.ids.tolist() == ids_t
    assert epoch.labels.tolist() == labels_t.tolist()
    for i in range(len(ids_t)):
        assert _row_triples(
            epoch.starts[i], epoch.paths[i], epoch.ends[i]
        ) == _row_triples(starts_t[i], paths_t[i], ends_t[i]), f"row {i}"


@pytest.mark.parametrize("seed", [0, 1])
def test_variable_epoch_matches_reference(tmp_path, seed):
    rng = np.random.default_rng(seed)
    corpus, path_idx, terminal_idx = _make_corpus(tmp_path, rng)
    theirs_reader, ours = _load_both(corpus, path_idx, terminal_idx, False, True)

    ids_t, starts_t, paths_t, ends_t, labels_t = _reference_build(
        theirs_reader, theirs_reader.items, L
    )
    epoch = build_variable_epoch(
        ours, np.arange(ours.n_items), L, np.random.default_rng(seed + 100)
    )

    # expansion order: items in order, aliases in insertion order — both
    # sides iterate the same way, so ids/labels match as SEQUENCES
    assert epoch.ids.tolist() == ids_t
    assert epoch.labels.tolist() == labels_t.tolist()
    for i in range(len(ids_t)):
        assert _row_triples(
            epoch.starts[i], epoch.paths[i], epoch.ends[i]
        ) == _row_triples(starts_t[i], paths_t[i], ends_t[i]), f"example {i}"


def test_variable_truncation_invariants(tmp_path):
    """Per-alias bags larger than L: both sides keep an L-subset of the
    alias's renamed contexts (truncate-after-filter+rename, dataset_builder
    .py:196-199). Dense corpora (~360 contexts/method over 26 terminals)
    push many aliases past L so the truncation branch genuinely runs."""
    rng = np.random.default_rng(5)
    corpus, path_idx, terminal_idx = _make_corpus(
        tmp_path, rng, n_methods=6, min_ctx=350, max_ctx=400
    )
    theirs_reader, ours = _load_both(corpus, path_idx, terminal_idx, False, True)

    _ids_t, starts_t, paths_t, ends_t, _labels_t = _reference_build(
        theirs_reader, theirs_reader.items, L
    )
    epoch = build_variable_epoch(
        ours, np.arange(ours.n_items), L, np.random.default_rng(6)
    )

    q = theirs_reader.QUESTION_TOKEN_INDEX
    stoi = theirs_reader.terminal_vocab.stoi
    row = 0
    truncated_rows = 0
    for item in theirs_reader.items:
        for alias_name in item.aliases:
            if not alias_name.startswith("@var_"):
                continue
            v = stoi[alias_name]
            full = Counter(
                (q if s == v else s, p, q if e == v else e)
                for s, p, e in item.path_contexts
                if s == v or e == v
            )
            want = min(sum(full.values()), L)
            if want == L and sum(full.values()) > L:
                truncated_rows += 1
            for side_name, (s_row, p_row, e_row) in {
                "ours": (epoch.starts[row], epoch.paths[row], epoch.ends[row]),
                "theirs": (starts_t[row], paths_t[row], ends_t[row]),
            }.items():
                picked = Counter(_row_triples(s_row, p_row, e_row))
                assert sum(picked.values()) == want, (side_name, row)
                assert all(picked[t] <= full[t] for t in picked), (side_name, row)
            row += 1
    assert row == len(epoch.ids) == len(starts_t)
    assert truncated_rows > 0, "corpus never exercised the truncation branch"


def test_method_subsample_invariants(tmp_path):
    """n > L rows: both sides draw a without-replacement L-subset of the
    item's substituted contexts (the draws differ; the invariant must not)."""
    rng = np.random.default_rng(3)
    corpus, path_idx, terminal_idx = _make_corpus(
        tmp_path, rng, min_ctx=L + 5, max_ctx=L + 15
    )
    theirs_reader, ours = _load_both(corpus, path_idx, terminal_idx, True, False)

    _ids_t, starts_t, paths_t, ends_t, _labels_t = _reference_build(
        theirs_reader, theirs_reader.items, L
    )
    epoch = build_method_epoch(
        ours, np.arange(ours.n_items), L, np.random.default_rng(4)
    )

    # full substituted context multiset per item, from the oracle reader
    # (reader parity is pinned by test_reader_vs_reference)
    q = theirs_reader.QUESTION_TOKEN_INDEX
    m = theirs_reader.terminal_vocab.stoi["@method_0"]
    for i, item in enumerate(theirs_reader.items):
        full = Counter(
            (q if s == m else s, p, q if e == m else e)
            for s, p, e in item.path_contexts
        )
        for side_name, (s_row, p_row, e_row) in {
            "ours": (epoch.starts[i], epoch.paths[i], epoch.ends[i]),
            "theirs": (starts_t[i], paths_t[i], ends_t[i]),
        }.items():
            picked = Counter(_row_triples(s_row, p_row, e_row))
            assert sum(picked.values()) == L, (side_name, i)
            assert all(picked[t] <= full[t] for t in picked), (side_name, i)
