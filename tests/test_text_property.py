"""Property-based parity: our label normalization vs the REFERENCE's own code.

The subtoken metrics (and hence every reported F1) sit on top of
``normalize_method_name``/``subtokenize``; a silent divergence from the
reference regexes would skew every quality number while all golden tests
still pass. These tests import the reference's actual ``Vocab`` from
/root/reference (skipped when the checkout is absent) and fuzz both
implementations with hypothesis over adversarial identifier shapes —
digit/underscore runs, caps runs (``HTMLParser``), unicode letters, and
arbitrary text — asserting byte-identical outputs.
"""

import pytest

hypothesis = pytest.importorskip("hypothesis")
from hypothesis import given, settings, strategies as st  # noqa: E402

from conftest import import_reference  # noqa: E402

ReferenceVocab = import_reference("model.dataset").Vocab

from code2vec_tpu.text import (  # noqa: E402
    normalize_method_name,
    subtokenize,
)

# identifier-ish strings: the shapes real corpora produce, plus hostile ones
_ident_chars = st.sampled_from(
    list("abcdefghijklmnopqrstuvwxyzABCDEFGHIJKLMNOPQRSTUVWXYZ0123456789_$")
)
identifiers = st.text(_ident_chars, min_size=0, max_size=40)
# anything at all — the reference applies these regexes to raw label text,
# so ours must match on arbitrary input too (unicode letters included)
arbitrary = st.text(min_size=0, max_size=40)


@settings(max_examples=2000, deadline=None)
@given(identifiers | arbitrary)
def test_normalize_matches_reference(name):
    assert normalize_method_name(name) == ReferenceVocab.normalize_method_name(
        name
    )


@settings(max_examples=2000, deadline=None)
@given(identifiers | arbitrary)
def test_subtokens_match_reference(name):
    # the reference subtokenizes the NORMALIZED name (dataset_reader.py:97-100)
    normalized = ReferenceVocab.normalize_method_name(name)
    assert subtokenize(normalized) == ReferenceVocab.get_method_subtokens(
        normalized
    )


@pytest.mark.parametrize(
    "name",
    [
        "toString",
        "HTMLParser",
        "a",
        "A",
        "_",
        "__init__",
        "get2ndValue",
        "parseHTTPResponse2JSON",
        "ALLCAPS",
        "snake_case_name",
        "ñiño",  # unicode lowercase: [a-z] must NOT match it, in both
        "ÉclairBuilder",
    ],
)
def test_known_edges_match_reference(name):
    assert normalize_method_name(name) == ReferenceVocab.normalize_method_name(
        name
    )
    normalized = ReferenceVocab.normalize_method_name(name)
    assert subtokenize(normalized) == ReferenceVocab.get_method_subtokens(
        normalized
    )
