"""Differential test: full training steps vs the reference implementation.

The strongest parity claim available: starting from IDENTICAL weights and
an IDENTICAL batch, several consecutive optimizer steps produce the same
losses and the same post-step parameters on both sides — which pins the
loss (log_softmax + class-weighted NLL with weighted-mean reduction,
reference main.py:129-130,251-262), the backward pass through the whole
model, and the optimizer (torch.optim.Adam with coupled L2 vs our
torch_style_adam optax chain) in one shot.

Batches come from OUR epoch builder and are fed to both sides verbatim —
builder parity has its own differential suite. Dropout is 0 so both
forwards are deterministic; steps reuse one batch so Adam's bias
correction is exercised at t = 1, 2, 3.
"""

import numpy as np
import pytest
import torch

from conftest import import_reference, make_reference_corpus

_ref_model_mod = import_reference("model.model")
ReferenceReader = import_reference("model.dataset_reader").DatasetReader

import jax  # noqa: E402

from code2vec_tpu.data.pipeline import build_method_epoch  # noqa: E402
from code2vec_tpu.data.reader import load_corpus  # noqa: E402
from code2vec_tpu.interop import from_param_tree  # noqa: E402
from code2vec_tpu.models.code2vec import Code2VecConfig  # noqa: E402
from code2vec_tpu.train.config import TrainConfig  # noqa: E402
from code2vec_tpu.train.loop import class_weights_from  # noqa: E402
from code2vec_tpu.train.step import build_train_step_fn, create_train_state  # noqa: E402

L = 16
ENCODE = 24
EMBED = 10


class _Option:
    """The slice of the reference's Option its Code2Vec reads."""

    def __init__(self, reader):
        self.terminal_count = reader.terminal_vocab.len()
        self.path_count = reader.path_vocab.len()
        self.label_count = reader.label_vocab.len()
        self.terminal_embed_size = EMBED
        self.path_embed_size = EMBED
        self.encode_size = ENCODE
        self.dropout_prob = 0.0
        self.angular_margin_loss = False


@pytest.mark.parametrize("weight_decay", [0.0, 0.01], ids=["wd0", "wd0.01"])
def test_train_steps_match_reference(tmp_path, weight_decay):
    rng = np.random.default_rng(11)
    corpus, path_idx, terminal_idx = make_reference_corpus(
        tmp_path, rng, n_methods=12, include_method_token=True
    )
    theirs_reader = ReferenceReader(
        str(corpus), str(path_idx), str(terminal_idx),
        infer_method=True, infer_variable=False,
        shuffle_variable_indexes=False,
    )
    ours_data = load_corpus(
        corpus, path_idx, terminal_idx, cache=False
    )

    config = TrainConfig(
        batch_size=ours_data.n_items, max_path_length=L,
        terminal_embed_size=EMBED, path_embed_size=EMBED, encode_size=ENCODE,
        dropout_prob=0.0, lr=0.01, beta_min=0.9, beta_max=0.999,
        weight_decay=weight_decay,
    )
    model_config = Code2VecConfig(
        terminal_count=len(ours_data.terminal_vocab),
        path_count=len(ours_data.path_vocab),
        label_count=len(ours_data.label_vocab),
        terminal_embed_size=EMBED, path_embed_size=EMBED, encode_size=ENCODE,
        dropout_prob=0.0, vocab_pad_multiple=1,
    )

    epoch = build_method_epoch(
        ours_data, np.arange(ours_data.n_items), L, np.random.default_rng(7)
    )
    batch = {
        "starts": epoch.starts,
        "paths": epoch.paths,
        "ends": epoch.ends,
        "labels": epoch.labels,
        "example_mask": np.ones(len(epoch.labels), np.float32),
    }

    class_weights = class_weights_from(config, ours_data)
    state = create_train_state(
        config, model_config, jax.random.PRNGKey(0), batch
    )
    train_step = build_train_step_fn(model_config, class_weights)

    # the reference side starts from OUR initial weights
    option = _Option(theirs_reader)
    ref_model = _ref_model_mod.Code2Vec(option)
    missing = ref_model.load_state_dict(
        {
            k: torch.from_numpy(np.array(v))
            for k, v in from_param_tree(
                jax.tree.map(np.asarray, state.params), model_config
            ).items()
        },
        strict=True,
    )
    assert not missing.missing_keys and not missing.unexpected_keys

    freq = torch.tensor(
        theirs_reader.label_vocab.get_freq_list(), dtype=torch.float32
    )
    criterion = torch.nn.NLLLoss(weight=1.0 / freq)
    optimizer = torch.optim.Adam(
        ref_model.parameters(), lr=config.lr,
        betas=(config.beta_min, config.beta_max),
        weight_decay=config.weight_decay,
    )
    starts_t = torch.from_numpy(batch["starts"]).long()
    paths_t = torch.from_numpy(batch["paths"]).long()
    ends_t = torch.from_numpy(batch["ends"]).long()
    labels_t = torch.from_numpy(batch["labels"]).long()

    ref_model.train()
    for step_i in range(3):
        optimizer.zero_grad()
        preds, _, _ = ref_model.forward(starts_t, paths_t, ends_t, labels_t)
        ref_loss = criterion(
            torch.nn.functional.log_softmax(preds, dim=1), labels_t
        )
        ref_loss.backward()
        optimizer.step()

        state, our_loss = train_step(state, batch)
        np.testing.assert_allclose(
            float(our_loss), float(ref_loss.detach()), rtol=2e-5,
            err_msg=f"loss diverged at step {step_i}",
        )

    ours_final = from_param_tree(
        jax.tree.map(np.asarray, state.params), model_config
    )
    theirs_final = {
        k: v.detach().numpy() for k, v in ref_model.state_dict().items()
    }
    assert set(ours_final) == set(theirs_final)
    for k in ours_final:
        np.testing.assert_allclose(
            ours_final[k], theirs_final[k], atol=3e-5, rtol=1e-4,
            err_msg=f"post-step parameter {k} diverged",
        )
