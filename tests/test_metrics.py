"""Golden tests for the three eval matchers (reference: main.py:300-359)."""

import numpy as np
import pytest

from code2vec_tpu.data.vocab import Vocab
from code2vec_tpu.metrics import (
    averaged_subtoken_match,
    evaluate,
    exact_match,
    subtoken_match,
)


@pytest.fixture
def vocab():
    v = Vocab()
    v.add("getvalue", subtokens=("get", "value"))  # 0
    v.add("setvaluecount", subtokens=("set", "value", "count"))  # 1
    v.add("run", subtokens=("run",))  # 2
    return v


class TestSubtokenMatch:
    def test_perfect(self, vocab):
        e = np.array([0, 1, 2])
        acc, p, r, f1 = subtoken_match(e, e, vocab)
        assert acc == p == r == f1 == 1.0

    def test_hand_computed(self, vocab):
        # expected getvalue(2 toks), predicted setvaluecount(3 toks):
        # matches: "value" -> 1; expected_count=2, actual_count=3
        e = np.array([0])
        a = np.array([1])
        acc, p, r, f1 = subtoken_match(e, a, vocab)
        assert acc == pytest.approx(1 / (2 + 3 - 1))
        assert p == pytest.approx(1 / 3)
        assert r == pytest.approx(1 / 2)
        assert f1 == pytest.approx(2 * (1 / 3) * (1 / 2) / (1 / 3 + 1 / 2))

    def test_pooled_not_averaged(self, vocab):
        # two examples pooled: (0 vs 2): 0 matches, e=2,a=1; (2 vs 2): 1,1,1
        e = np.array([0, 2])
        a = np.array([2, 2])
        acc, p, r, f1 = subtoken_match(e, a, vocab)
        assert p == pytest.approx(1 / 2)  # 1 match / 2 actual
        assert r == pytest.approx(1 / 3)  # 1 match / 3 expected
        assert acc == pytest.approx(1 / (3 + 2 - 1))

    def test_no_overlap(self, vocab):
        acc, p, r, f1 = subtoken_match(np.array([0]), np.array([2]), vocab)
        assert (acc, p, r, f1) == (0.0, 0.0, 0.0, 0.0)


class TestAveragedSubtokenMatch:
    def test_mean_of_per_example(self, vocab):
        e = np.array([0, 2])
        a = np.array([1, 2])
        acc, p, r, f1 = averaged_subtoken_match(e, a, vocab)
        # ex1: match=1 -> acc 1/4, p 1/3, r 1/2, f1 0.4; ex2: all 1.0
        assert acc == pytest.approx((1 / 4 + 1.0) / 2)
        assert p == pytest.approx((1 / 3 + 1.0) / 2)
        assert r == pytest.approx((1 / 2 + 1.0) / 2)
        assert f1 == pytest.approx((0.4 + 1.0) / 2)


class TestExactMatch:
    def test_accuracy(self):
        e = np.array([0, 1, 2, 2])
        a = np.array([0, 1, 1, 2])
        acc, p, r, f1 = exact_match(e, a)
        assert acc == pytest.approx(0.75)
        assert 0 < f1 <= 1


class TestDispatch:
    def test_unknown_method_raises(self, vocab):
        with pytest.raises(ValueError):
            evaluate("bogus", np.array([0]), np.array([0]), vocab)

    def test_dispatches(self, vocab):
        e = np.array([0, 1])
        for method in ("exact", "subtoken", "ave_subtoken"):
            out = evaluate(method, e, e, vocab)
            assert len(out) == 4 and out[3] == pytest.approx(1.0)
