"""Test config: force an 8-device virtual CPU platform for the whole suite.

This is the TPU-pod analogue of a fake backend (SURVEY.md §4): pjit/shard_map
logic runs on 8 virtual CPU devices, no pod required.

jax may already be imported by pytest plugins (jaxtyping), but backends
initialize lazily, so env + jax.config updates here still take effect as long
as no devices were touched yet.
"""

import os

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ["JAX_PLATFORMS"] = "cpu"

import jax

jax.config.update("jax_platforms", "cpu")

assert jax.default_backend() == "cpu" and jax.device_count() >= 8, (
    "tests require the 8-device virtual CPU platform; a real backend was "
    "initialized before tests/conftest.py could force it — run pytest from "
    "the repo root"
)


def pytest_report_header(config):
    return f"jax devices: {jax.device_count()} ({jax.default_backend()})"
