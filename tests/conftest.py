"""Test config: force an 8-device virtual CPU platform for the whole suite.

This is the TPU-pod analogue of a fake backend (SURVEY.md §4): pjit/shard_map
logic runs on 8 virtual CPU devices, no pod required.

jax may already be imported by pytest plugins (jaxtyping), but backends
initialize lazily, so env + jax.config updates here still take effect as long
as no devices were touched yet.
"""

import os

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ["JAX_PLATFORMS"] = "cpu"

import jax

jax.config.update("jax_platforms", "cpu")

assert jax.default_backend() == "cpu" and jax.device_count() >= 8, (
    "tests require the 8-device virtual CPU platform; a real backend was "
    "initialized before tests/conftest.py could force it — run pytest from "
    "the repo root"
)


def pytest_report_header(config):
    return f"jax devices: {jax.device_count()} ({jax.default_backend()})"


def import_reference(module_name: str):
    """Import a module from the reference checkout for oracle tests.

    Skips the calling module when the checkout (CODE2VEC_REFERENCE, default
    /root/reference) or its dependencies (torch) are absent, and keeps the
    checkout off sys.path afterwards — its root main.py / model package
    could shadow repo modules.
    """
    import importlib
    import sys as _sys

    import pytest as _pytest

    reference = os.environ.get("CODE2VEC_REFERENCE", "/root/reference")
    if not os.path.isdir(os.path.join(reference, "model")):
        _pytest.skip("reference checkout not available", allow_module_level=True)
    _sys.path.insert(0, reference)
    try:
        return importlib.import_module(module_name)
    except ImportError as exc:
        _pytest.skip(
            f"reference {module_name} not importable: {exc}",
            allow_module_level=True,
        )
    finally:
        _sys.path.remove(reference)
