"""Test config: force an 8-device virtual CPU platform for the whole suite.

This is the TPU-pod analogue of a fake backend (SURVEY.md §4): pjit/shard_map
logic runs on 8 virtual CPU devices, no pod required.

jax may already be imported by pytest plugins (jaxtyping), but backends
initialize lazily, so env + jax.config updates here still take effect as long
as no devices were touched yet.
"""

import os

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ["JAX_PLATFORMS"] = "cpu"
# the suite's kernel calls default to the Pallas INTERPRETER: the parity
# tests exist to validate the TPU kernel bodies on CPU, and the pre-backend
# suites were written against that behavior. The CI kernel-portability job
# (and any caller) overrides with C2V_KERNEL_BACKEND=cpu to run the same
# suites through the compiled CPU strategy instead (ops/backend.py);
# setdefault keeps that override — and per-test monkeypatching — working.
os.environ.setdefault("C2V_KERNEL_BACKEND", "interpret")
# subprocess-spawning tests (multiprocess workers, tool drives) inherit the
# compile cache through the env var form of the same knob. Per-user suffix:
# a fixed /tmp path collides across users on shared machines (permission
# errors, unbounded growth); a pre-set env var wins so operators can pin it.
# Per-CPU-feature suffix: XLA's cached executables embed the compiling
# host's ISA features, and reusing a cache written on a different host logs
# "machine features mismatch ... could lead to SIGILL" (BENCH_r05) — on a
# shared filesystem each CPU population must get its own cache dir.
# (obs.runtime is stdlib-only; importing it here initializes no backend.)
from code2vec_tpu.obs.runtime import host_cpu_fingerprint

os.environ.setdefault(
    "JAX_COMPILATION_CACHE_DIR",
    f"/tmp/jaxcache_tests_{getattr(os, 'getuid', lambda: 'na')()}"
    f"_{host_cpu_fingerprint()}",
)
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "0.5")

import jax

jax.config.update("jax_platforms", "cpu")
# persistent XLA compile cache: the suite is dominated by jit compiles
# (VERDICT r4 weak-#6 — 19m at 479 tests, superlinear growth), and the
# programs are identical across runs; keyed by HLO+topology hash, so it is
# safe across code changes and the 8-device virtual platform. Read back
# from the env var (NOT a hardcoded path) so in-process tests and spawned
# subprocesses always share one cache, including when the var was pre-set
jax.config.update(
    "jax_compilation_cache_dir", os.environ["JAX_COMPILATION_CACHE_DIR"]
)
jax.config.update(
    "jax_persistent_cache_min_compile_time_secs",
    float(os.environ["JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS"]),
)

assert jax.default_backend() == "cpu" and jax.device_count() >= 8, (
    "tests require the 8-device virtual CPU platform; a real backend was "
    "initialized before tests/conftest.py could force it — run pytest from "
    "the repo root"
)


def pytest_report_header(config):
    return f"jax devices: {jax.device_count()} ({jax.default_backend()})"


import pytest  # noqa: E402


@pytest.fixture
def zero_leaked_handles():
    """Assert every handle the test opened was closed again by its end.

    Inert when the handle ledger is off (the default): production runs
    pay nothing, and the plain suite behaves exactly as before. With
    ``C2V_HANDLE_DEBUG=1`` (the lifecycle CI job, or a local repro run)
    it diffs the ledger's monotone open tokens across the test — any
    token opened during the test and still open at the end fails with
    the handle's kind, name, and creation site.
    """
    from code2vec_tpu.obs import handles

    if not handles.handle_debug_enabled():
        yield
        return
    before = {r["token"] for r in handles.open_handles()}
    yield
    leaked = [
        r for r in handles.open_handles() if r["token"] not in before
    ]
    assert not leaked, (
        f"{len(leaked)} handle(s) leaked by this test: "
        + "; ".join(
            f"{r['kind']} '{r['name']}' created at\n{r['site']}"
            for r in leaked
        )
    )


def make_reference_corpus(
    tmp_path,
    rng,
    *,
    n_methods=20,
    n_terminals=28,
    n_paths=32,
    n_vars=4,
    min_ctx=1,
    max_ctx=12,
    label_fn=None,
    alias_fn=None,
    include_method_token=False,
):
    """Write a random corpus + idx files for reference-oracle tests.

    Shared by the reader/builder differential suites so the corpus format
    lives in one place. ``label_fn(i, rng) -> str`` and
    ``alias_fn(i, v, rng) -> str`` customize label/alias-original naming
    (defaults: unique per method / per alias). Returns
    (corpus, path_idx, terminal_idx) paths.
    """
    from code2vec_tpu.formats.corpus_io import CorpusRecord, write_corpus
    from code2vec_tpu.formats.vocab_io import write_vocab_from_names

    if label_fn is None:
        label_fn = lambda i, _rng: f"method{i}Name"  # noqa: E731
    if alias_fn is None:
        alias_fn = lambda i, v, _rng: f"orig{i}Var{v}"  # noqa: E731
    plain = n_terminals - n_vars - (1 if include_method_token else 0)
    terminal_names = [f"term{i}" for i in range(plain)]
    if include_method_token:
        terminal_names.append("@method_0")
    terminal_names += [f"@var_{i}" for i in range(n_vars)]
    if not include_method_token:
        rng.shuffle(terminal_names)
    write_vocab_from_names(tmp_path / "terminal_idxs.txt", terminal_names)
    write_vocab_from_names(
        tmp_path / "path_idxs.txt", [f"path{i}" for i in range(n_paths)]
    )
    records = []
    for i in range(n_methods):
        n_ctx = int(rng.integers(min_ctx, max_ctx + 1))
        contexts = [
            (
                int(rng.integers(0, n_terminals)),
                int(rng.integers(1, n_paths + 1)),
                int(rng.integers(0, n_terminals)),
            )
            for _ in range(n_ctx)
        ]
        aliases = [
            (alias_fn(i, v, rng), f"@var_{v}")
            for v in range(int(rng.integers(0, n_vars)))
        ]
        records.append(
            CorpusRecord(
                id=i * 7 + 1,
                label=label_fn(i, rng),
                source=f"com/example/C{i}.java",
                path_contexts=contexts,
                aliases=aliases,
            )
        )
    corpus = tmp_path / "corpus.txt"
    write_corpus(corpus, records)
    return corpus, tmp_path / "path_idxs.txt", tmp_path / "terminal_idxs.txt"


def import_reference(module_name: str):
    """Import a module from the reference checkout for oracle tests.

    Skips the calling module when the checkout (CODE2VEC_REFERENCE, default
    /root/reference) or its dependencies (torch) are absent, and keeps the
    checkout off sys.path afterwards — its root main.py / model package
    could shadow repo modules.
    """
    import importlib
    import sys as _sys

    import pytest as _pytest

    reference = os.environ.get("CODE2VEC_REFERENCE", "/root/reference")
    if not os.path.isdir(os.path.join(reference, "model")):
        _pytest.skip("reference checkout not available", allow_module_level=True)
    _sys.path.insert(0, reference)
    try:
        return importlib.import_module(module_name)
    except ImportError as exc:
        _pytest.skip(
            f"reference {module_name} not importable: {exc}",
            allow_module_level=True,
        )
    finally:
        _sys.path.remove(reference)
