"""Tests for the reader + vectorized epoch pipeline (SURVEY.md §2.5-2.6)."""

import numpy as np
import pytest

from code2vec_tpu import PAD_INDEX, QUESTION_TOKEN_INDEX
from code2vec_tpu.data.pipeline import (
    build_epoch,
    build_method_epoch,
    build_variable_epoch,
    iter_batches,
    oov_rate,
    split_items,
)
from code2vec_tpu.data.reader import load_corpus
from code2vec_tpu.data.synth import SPECS, SynthSpec, generate_corpus_files


@pytest.fixture(scope="module")
def tiny_corpus(tmp_path_factory):
    out = tmp_path_factory.mktemp("tiny")
    paths = generate_corpus_files(out, SPECS["tiny"])
    return paths


@pytest.fixture(scope="module")
def tiny_data(tiny_corpus):
    return load_corpus(
        tiny_corpus["corpus"],
        tiny_corpus["path_idx"],
        tiny_corpus["terminal_idx"],
        infer_method=True,
        infer_variable=True,
    )


class TestReader:
    def test_shapes_consistent(self, tiny_data):
        d = tiny_data
        assert d.n_items == 200
        assert len(d.starts) == len(d.paths) == len(d.ends) == d.n_contexts
        assert d.row_splits[0] == 0 and d.row_splits[-1] == d.n_contexts
        assert (np.diff(d.row_splits) >= 0).all()

    def test_question_shift_applied(self, tiny_data):
        # @method_0 raw idx 1 -> shifted 2; @question occupies 1
        assert tiny_data.terminal_vocab.stoi["@question"] == QUESTION_TOKEN_INDEX
        assert tiny_data.method_token_index == 2
        # paths are NOT shifted
        assert tiny_data.paths.min() >= 1

    def test_labels_built_in_order(self, tiny_data):
        assert tiny_data.labels.min() >= 0
        assert len(tiny_data.label_vocab) > 0
        # every label id resolves to subtokens
        for i in range(len(tiny_data.label_vocab)):
            assert tiny_data.label_vocab.itos[i]

    def test_variable_indexes(self, tiny_data):
        names = [tiny_data.terminal_vocab.itos[i] for i in tiny_data.variable_indexes]
        assert all(n.startswith("@var_") for n in names)
        assert len(names) == SPECS["tiny"].n_vars


class TestSplit:
    def test_deterministic(self):
        a = split_items(100, np.random.default_rng(7))
        b = split_items(100, np.random.default_rng(7))
        np.testing.assert_array_equal(a[0], b[0])
        np.testing.assert_array_equal(a[1], b[1])

    def test_ratio_and_disjoint(self):
        train, test = split_items(100, np.random.default_rng(0), 0.2)
        assert len(test) == 20 and len(train) == 80
        assert not set(train) & set(test)


class TestMethodEpoch:
    def test_static_shape_and_padding(self, tiny_data):
        idx = np.arange(tiny_data.n_items)
        ep = build_method_epoch(tiny_data, idx, 50, np.random.default_rng(0))
        assert ep.starts.shape == (tiny_data.n_items, 50)
        counts = tiny_data.context_counts()
        for i in [0, 5, 17]:
            n_real = min(int(counts[i]), 50)
            assert (ep.starts[i, :n_real] != PAD_INDEX).all()
            assert (ep.starts[i, n_real:] == PAD_INDEX).all()
            assert (ep.paths[i, n_real:] == PAD_INDEX).all()

    def test_subsample_is_subset_of_method_contexts(self, tiny_data):
        idx = np.arange(10)
        ep = build_method_epoch(tiny_data, idx, 8, np.random.default_rng(1))
        for i in range(10):
            lo, hi = tiny_data.row_splits[i], tiny_data.row_splits[i + 1]
            legal_paths = set(tiny_data.paths[lo:hi].tolist())
            got = [p for p in ep.paths[i] if p != PAD_INDEX]
            assert set(got) <= legal_paths
            assert len(got) == min(hi - lo, 8)

    def test_no_method_token_leak(self, tiny_data):
        idx = np.arange(tiny_data.n_items)
        ep = build_method_epoch(tiny_data, idx, 200, np.random.default_rng(2))
        m = tiny_data.method_token_index
        assert not (ep.starts == m).any()
        assert not (ep.ends == m).any()
        # and substitution produced @question somewhere (synth sprinkles it)
        assert (ep.starts == QUESTION_TOKEN_INDEX).any()

    def test_resampling_differs_across_epochs(self, tiny_data):
        idx = np.arange(tiny_data.n_items)
        rng = np.random.default_rng(3)
        a = build_method_epoch(tiny_data, idx, 10, rng)
        b = build_method_epoch(tiny_data, idx, 10, rng)
        assert (a.paths != b.paths).any()

    def test_matches_naive_reference_semantics(self, tiny_data):
        # Oracle: per-method "shuffle then take first L" yields some subset
        # of size min(n, L); verify the vectorized path produces exactly a
        # permutation-invariant subset with correct multiplicity.
        idx = np.asarray([3])
        ep = build_method_epoch(tiny_data, idx, 5, np.random.default_rng(4))
        lo, hi = tiny_data.row_splits[3], tiny_data.row_splits[3 + 1]
        bag = list(
            zip(
                tiny_data.starts[lo:hi].tolist(),
                tiny_data.paths[lo:hi].tolist(),
                tiny_data.ends[lo:hi].tolist(),
            )
        )
        m = tiny_data.method_token_index
        bag = [
            (
                QUESTION_TOKEN_INDEX if s == m else s,
                p,
                QUESTION_TOKEN_INDEX if e == m else e,
            )
            for s, p, e in bag
        ]
        got = [
            (int(s), int(p), int(e))
            for s, p, e in zip(ep.starts[0], ep.paths[0], ep.ends[0])
            if p != PAD_INDEX
        ]
        # multiset containment
        from collections import Counter

        assert not Counter(got) - Counter(bag)
        assert len(got) == min(len(bag), 5)


class TestVariableEpoch:
    def test_examples_per_alias(self, tiny_data):
        idx = np.arange(tiny_data.n_items)
        ep = build_variable_epoch(tiny_data, idx, 20, np.random.default_rng(0))
        expected = sum(
            len([a for a in tiny_data.aliases[i] if a.startswith("@var_")])
            for i in range(tiny_data.n_items)
        )
        assert len(ep) == expected

    def test_target_renamed_to_question(self, tiny_data):
        idx = np.arange(tiny_data.n_items)
        ep = build_variable_epoch(tiny_data, idx, 20, np.random.default_rng(0))
        var_ids = set(tiny_data.variable_indexes.tolist())
        for r in range(len(ep)):
            row = [
                (int(s), int(e))
                for s, e in zip(ep.starts[r], ep.ends[r])
                if (s, e) != (PAD_INDEX, PAD_INDEX) and ep.paths[r][0] != PAD_INDEX
            ]
            # every example must mention @question at least once
            flat = [v for se in row for v in se]
            if row:
                assert QUESTION_TOKEN_INDEX in flat

    def test_plain_identifiers_untouched_by_remap(self, tiny_data):
        # regression: ids above max(@var id) must pass through the remap
        # table untouched (clamping used to rewrite them to @var tokens)
        from code2vec_tpu.data.pipeline import _index_remap, _rename_target

        var_ids = np.asarray([3, 4, 5], np.int32)
        table = _index_remap(var_ids, var_ids[::-1].copy())
        values = np.asarray([3, 100, 250, 4], np.int32)
        out = _rename_target(values, target_idx=3, perm_map=table)
        assert out.tolist() == [QUESTION_TOKEN_INDEX, 100, 250, 4]

    def test_shuffle_variable_indexes_remaps(self, tiny_data):
        idx = np.arange(tiny_data.n_items)
        a = build_variable_epoch(
            tiny_data, idx, 20, np.random.default_rng(5), shuffle_variable_indexes=False
        )
        b = build_variable_epoch(
            tiny_data, idx, 20, np.random.default_rng(5), shuffle_variable_indexes=True
        )
        assert len(a) == len(b)


class TestBatches:
    def test_static_batches_with_mask(self, tiny_data):
        ep = build_epoch(
            tiny_data, np.arange(50), 16, np.random.default_rng(0)
        )
        batches = list(iter_batches(ep, batch_size=32, rng=np.random.default_rng(1)))
        assert all(b["starts"].shape == (32, 16) for b in batches)
        total_valid = sum(int(b["example_mask"].sum()) for b in batches)
        assert total_valid == len(ep)
        # all but last fully valid
        assert all(b["example_mask"].all() for b in batches[:-1])

    def test_drop_remainder(self, tiny_data):
        ep = build_epoch(tiny_data, np.arange(50), 16, np.random.default_rng(0))
        batches = list(iter_batches(ep, 32, np.random.default_rng(1), pad_final=False))
        assert len(batches) == len(ep) // 32


class TestOOV:
    def test_range_and_determinism(self, tiny_data):
        train, test = split_items(tiny_data.n_items, np.random.default_rng(0))
        r = oov_rate(tiny_data, train, test)
        assert 0.0 <= r <= 1.0
        assert r == oov_rate(tiny_data, train, test)


class TestSynthFiles:
    def test_params_written(self, tiny_corpus):
        from code2vec_tpu.formats import read_params

        params = read_params(tiny_corpus["params"])
        assert params["method_count"] == "200"
        assert params["max_length"] == "8"


class TestCorpusCache:
    def _load(self, paths, **kw):
        return load_corpus(
            paths["corpus"], paths["path_idx"], paths["terminal_idx"], **kw
        )

    def test_cache_round_trip_identical(self, tmp_path):
        import os

        paths = generate_corpus_files(tmp_path, SPECS["tiny"])
        import glob

        cold = self._load(paths, infer_method=True, infer_variable=True)
        assert glob.glob(str(paths["corpus"]) + ".cache-*.npz")
        warm = self._load(paths, infer_method=True, infer_variable=True)
        np.testing.assert_array_equal(cold.starts, warm.starts)
        np.testing.assert_array_equal(cold.paths, warm.paths)
        np.testing.assert_array_equal(cold.ends, warm.ends)
        np.testing.assert_array_equal(cold.row_splits, warm.row_splits)
        np.testing.assert_array_equal(cold.labels, warm.labels)
        np.testing.assert_array_equal(
            cold.variable_indexes, warm.variable_indexes
        )
        assert cold.normalized_labels == warm.normalized_labels
        assert cold.sources == warm.sources
        assert cold.aliases == warm.aliases
        assert cold.label_vocab.stoi == warm.label_vocab.stoi
        assert cold.label_vocab.freq == warm.label_vocab.freq
        assert (
            cold.label_vocab.itosubtokens == warm.label_vocab.itosubtokens
        )

    def test_cache_invalidated_on_corpus_change(self, tmp_path):
        import os

        paths = generate_corpus_files(tmp_path, SPECS["tiny"])
        self._load(paths)
        # append a record: size/mtime change must invalidate the cache
        with open(paths["corpus"], "a", encoding="utf-8") as f:
            f.write("#9999\nlabel:extraMethod\nclass:X.java\npaths:\n1\t1\t1\n\n")
        fresh = self._load(paths)
        assert fresh.n_items == SPECS["tiny"].n_methods + 1
        assert "extramethod" in fresh.label_vocab.stoi  # normalized label present

    def test_cache_keyed_on_task_flags(self, tmp_path):
        paths = generate_corpus_files(tmp_path, SPECS["tiny"])
        method_only = self._load(paths, infer_method=True, infer_variable=False)
        both = self._load(paths, infer_method=True, infer_variable=True)
        # the variable task adds @var_* original names to the label vocab;
        # strict > proves the second load did NOT reuse the first's cache
        assert len(both.label_vocab) > len(method_only.label_vocab)
        # and a second method-only load hits its own (flag-keyed) cache
        again = self._load(paths, infer_method=True, infer_variable=False)
        assert again.label_vocab.stoi == method_only.label_vocab.stoi

    def test_corrupt_cache_degrades_to_reparse(self, tmp_path):
        import glob

        paths = generate_corpus_files(tmp_path, SPECS["tiny"])
        cold = self._load(paths)
        npz = glob.glob(str(paths["corpus"]) + ".cache-*.npz")[0]
        with open(npz, "wb") as f:
            f.write(b"not a zip file")
        recovered = self._load(paths)  # must warn + reparse, not crash
        np.testing.assert_array_equal(cold.starts, recovered.starts)

    def test_cache_off(self, tmp_path):
        import glob

        paths = generate_corpus_files(tmp_path, SPECS["tiny"])
        self._load(paths, cache=False)
        # no sidecar of any naming scheme may appear (digest-keyed included)
        assert glob.glob(str(paths["corpus"]) + ".cache*") == []


class TestNativeCorpusParse:
    def test_native_parser_loads(self, tmp_path):
        """parse_corpus_native must actually run (no silent fallback):
        a build/ABI regression fails here instead of being masked by
        load_corpus's Python-parser fallback."""
        from code2vec_tpu.extractor import parse_corpus_native

        paths = generate_corpus_files(tmp_path, SPECS["tiny"])
        starts, cpaths, ends, row_splits, ids, headers, var_lists = (
            parse_corpus_native(paths["corpus"])
        )
        assert len(row_splits) == SPECS["tiny"].n_methods + 1
        assert len(headers) == len(var_lists) == SPECS["tiny"].n_methods
        assert len(starts) == len(cpaths) == len(ends) == row_splits[-1]

    def test_native_matches_python_parser(self, tmp_path, caplog):
        """The C++ corpus parser and the Python state machine must agree
        on every field, including label-vocab insertion order."""
        import logging

        paths = generate_corpus_files(tmp_path, SPECS["tiny"])
        kw = dict(infer_method=True, infer_variable=True, cache=False)
        py = load_corpus(paths["corpus"], paths["path_idx"],
                         paths["terminal_idx"], native=False, **kw)
        with caplog.at_level(logging.WARNING):
            nat = load_corpus(paths["corpus"], paths["path_idx"],
                              paths["terminal_idx"], native=True, **kw)
        assert "native corpus parser unavailable" not in caplog.text
        np.testing.assert_array_equal(py.starts, nat.starts)
        np.testing.assert_array_equal(py.paths, nat.paths)
        np.testing.assert_array_equal(py.ends, nat.ends)
        np.testing.assert_array_equal(py.row_splits, nat.row_splits)
        np.testing.assert_array_equal(py.ids, nat.ids)
        np.testing.assert_array_equal(py.labels, nat.labels)
        assert py.sources == nat.sources
        assert py.aliases == nat.aliases
        assert py.normalized_labels == nat.normalized_labels
        assert py.label_vocab.stoi == nat.label_vocab.stoi
        assert py.label_vocab.itosubtokens == nat.label_vocab.itosubtokens

    def test_native_handles_edge_records(self, tmp_path):
        """Records with no #id, no class:, a doc: line, trailing columns in
        path rows, and a missing final blank line."""
        corpus = tmp_path / "corpus.txt"
        corpus.write_text(
            "#7\nlabel:getFoo\nclass:A.java\ndoc:ignored\npaths:\n"
            "1\t2\t3\n4\t5\t6\textra\nvars:\ncounter\t@var_0\n"
            "\n"
            "label:setBar\npaths:\n7\t8\t9"  # no id, no class, no final \n
        )
        term = tmp_path / "terminal_idxs.txt"
        term.write_text("0\t<PAD/>\n1\t@var_0\n" + "".join(
            f"{i}\tt{i}\n" for i in range(2, 11)))
        pathv = tmp_path / "path_idxs.txt"
        pathv.write_text("0\t<PAD/>\n" + "".join(
            f"{i}\tp{i}\n" for i in range(1, 10)))
        kw = dict(infer_method=True, infer_variable=True, cache=False)
        py = load_corpus(corpus, pathv, term, native=False, **kw)
        from code2vec_tpu.extractor import parse_corpus_native

        parse_corpus_native(corpus)  # direct: no fallback can mask failure
        nat = load_corpus(corpus, pathv, term, native=True, **kw)
        assert nat.n_items == 2 and nat.n_contexts == 3
        np.testing.assert_array_equal(py.starts, nat.starts)
        np.testing.assert_array_equal(py.ids, nat.ids)
        assert py.sources == nat.sources == ["A.java", None]
        assert py.aliases == nat.aliases

    def test_native_rejects_malformed_paths(self, tmp_path):
        """Corruption must fail the native parse loudly (then load_corpus
        falls back to the Python parser, which raises too) — never silent
        zeros in the context arrays."""
        from code2vec_tpu.extractor import parse_corpus_native

        corpus = tmp_path / "bad.txt"
        corpus.write_text("#0\nlabel:x\npaths:\n1\t2\n\n")  # 2 fields
        with pytest.raises(RuntimeError, match="malformed path-context"):
            parse_corpus_native(corpus)

    @pytest.mark.parametrize(
        "line",
        [
            "1 2 3",  # space-separated: split("\t") leaves one field
            "1x\t2\t3",  # intra-field garbage: int("1x") raises
            "1\t2\t3x",  # garbage in the last counted field
        ],
    )
    def test_native_rejects_nonint_path_fields(self, tmp_path, line):
        """Python-parser parity: int(line.split('\\t')[k]) rejects anything
        but a complete tab-separated integer per field."""
        from code2vec_tpu.extractor import parse_corpus_native

        corpus = tmp_path / "bad.txt"
        corpus.write_text(f"#0\nlabel:x\npaths:\n{line}\n\n")
        with pytest.raises(RuntimeError, match="malformed path-context"):
            parse_corpus_native(corpus)

    def test_native_accepts_trailing_path_columns(self, tmp_path):
        from code2vec_tpu.extractor import parse_corpus_native

        corpus = tmp_path / "ok.txt"
        corpus.write_text("#0\nlabel:x\npaths:\n1\t2\t3\tweight=0.5\n\n")
        starts, cpaths, ends, *_ = parse_corpus_native(corpus)
        assert (starts[0], cpaths[0], ends[0]) == (1, 2, 3)

    def test_native_rejects_malformed_id(self, tmp_path):
        """int(line[1:]) parity: '#12abc' must fail, not parse as 12."""
        from code2vec_tpu.extractor import parse_corpus_native

        corpus = tmp_path / "bad_id.txt"
        corpus.write_text("#12abc\nlabel:x\npaths:\n1\t2\t3\n\n")
        with pytest.raises(RuntimeError, match="malformed record id"):
            parse_corpus_native(corpus)

    def test_native_rejects_tabless_vars(self, tmp_path):
        from code2vec_tpu.extractor import parse_corpus_native

        corpus = tmp_path / "bad2.txt"
        corpus.write_text("#0\nlabel:x\npaths:\n1\t2\t3\nvars:\nnotab\n\n")
        with pytest.raises(RuntimeError, match="malformed vars"):
            parse_corpus_native(corpus)
