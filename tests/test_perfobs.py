"""Device-time/MFU/capacity accounting + perf-regression sentinel (PR 17).

Covers obs/costs.py (static cost extraction with its full degradation
matrix, the CostAccountant's O(1) dynamic accounting, fleet capacity
math), the serve integration (provenance cost records on a REAL compiled
ladder, XLA-vs-analytic FLOP agreement, batcher-fed device time), the
StepProfiler mfu column, the build_info gauge, the live ``flights`` op,
``trace_stitch --trace-id``, and ``tools/perf_report.py``'s exit codes
(clean run -> 0, injected regression -> nonzero).
"""

from __future__ import annotations

import json
import os
import sys

import numpy as np
import pytest

from code2vec_tpu.obs import costs as obs_costs
from code2vec_tpu.obs.costs import (
    CostAccountant,
    analytic_forward_cost,
    executable_cost,
    extract_cost,
    fleet_capacity,
    peak_flops,
    train_step_cost,
)
from code2vec_tpu.obs.runtime import (
    RuntimeHealth,
    build_info,
    build_info_text,
    parse_prometheus_text,
)

pytestmark = pytest.mark.perfobs

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "tools"))

import perf_report  # noqa: E402
import trace_stitch  # noqa: E402


# ---------------------------------------------------------------------------
# static cost model: extraction + degradation matrix


class TestExtractCost:
    def test_none_returns_none(self):
        assert extract_cost(None) is None

    def test_missing_flops_key_returns_none(self):
        assert extract_cost({"bytes accessed": 100.0}) is None
        assert extract_cost([{"transcendentals": 3.0}]) is None

    def test_empty_containers_return_none(self):
        assert extract_cost([]) is None
        assert extract_cost({}) is None
        assert extract_cost("not a dict") is None

    def test_bare_dict(self):
        got = extract_cost({"flops": 100.0, "bytes accessed": 400.0})
        assert got == {"flops": 100.0, "bytes_accessed": 400.0}

    def test_cpu_style_list_of_one_dict(self):
        # what jax CPU actually returns: a list holding one properties dict
        got = extract_cost([{"flops": 88035.0, "bytes accessed": 280876.0,
                             "transcendentals": 128.0}])
        assert got["flops"] == 88035.0
        assert got["bytes_accessed"] == 280876.0

    def test_per_primitive_dicts_are_summed(self):
        got = extract_cost([
            {"flops": 60.0, "bytes accessed": 10.0},
            {"flops": 40.0},
            {"not_a_cost": 1.0},
        ])
        assert got["flops"] == 100.0
        assert got["bytes_accessed"] == 10.0

    def test_garbage_values_rejected(self):
        assert extract_cost({"flops": float("nan")}) is None
        assert extract_cost({"flops": -1.0}) is None
        assert extract_cost({"flops": "huge"}) is None
        assert extract_cost({"flops": float("inf")}) is None


class _Compiled:
    """Fake compiled executable with a configurable cost_analysis()."""

    def __init__(self, result=None, raises=False):
        self._result = result
        self._raises = raises

    def cost_analysis(self):
        if self._raises:
            raise NotImplementedError("backend has no cost model")
        return self._result


ANALYTIC = analytic_forward_cost(
    8, 32, terminal_embed=16, path_embed=16, encode=24, labels=100
)


class TestExecutableCost:
    def test_xla_source_when_backend_reports(self):
        got = executable_cost(
            _Compiled([{"flops": 704280.0, "bytes accessed": 1000.0}]),
            ANALYTIC,
        )
        assert got["cost_source"] == "xla"
        assert got["flops"] == 704280.0
        assert got["arithmetic_intensity"] == pytest.approx(704.28)

    def test_analytic_fallback_when_backend_returns_none(self):
        got = executable_cost(_Compiled(None), ANALYTIC)
        assert got["cost_source"] == "analytic"
        assert got["flops"] == ANALYTIC["flops"]

    def test_analytic_fallback_when_backend_raises(self):
        got = executable_cost(_Compiled(raises=True), ANALYTIC)
        assert got["cost_source"] == "analytic"

    def test_analytic_fallback_on_missing_keys(self):
        got = executable_cost(_Compiled([{"transcendentals": 5.0}]), ANALYTIC)
        assert got["cost_source"] == "analytic"

    def test_no_compiled_no_analytic_is_explicitly_unknown(self):
        got = executable_cost(None, None)
        assert got == {"flops": None, "bytes_accessed": None,
                       "arithmetic_intensity": None, "cost_source": None}

    def test_object_without_cost_analysis_degrades(self):
        got = executable_cost(object(), ANALYTIC)
        assert got["cost_source"] == "analytic"

    def test_xla_flops_with_analytic_bytes_backfill(self):
        got = executable_cost(_Compiled({"flops": 500.0}), ANALYTIC)
        assert got["cost_source"] == "xla"
        assert got["flops"] == 500.0
        assert got["bytes_accessed"] == ANALYTIC["bytes_accessed"]


def test_train_step_cost_is_three_forwards():
    step = train_step_cost(ANALYTIC)
    assert step["flops"] == pytest.approx(3.0 * ANALYTIC["flops"])
    assert step["cost_source"] == "analytic"


class TestPeakFlops:
    def test_known_kinds(self):
        assert peak_flops("TPU v4") == 275e12
        assert peak_flops("NVIDIA A100-SXM4-80GB") == 312e12
        assert peak_flops("TPU v5 lite") == 197e12  # v5e before v5

    def test_unknown_kind_uses_cpu_formula(self):
        expected = 256e9 * (os.cpu_count() or 1)
        assert peak_flops("cpu") == expected
        assert peak_flops(None) == expected

    def test_env_override_wins(self, monkeypatch):
        monkeypatch.setenv("C2V_PEAK_FLOPS", "123456.0")
        assert peak_flops("TPU v4") == 123456.0
        monkeypatch.setenv("C2V_PEAK_FLOPS", "not a number")
        assert peak_flops("TPU v4") == 275e12


# ---------------------------------------------------------------------------
# dynamic accounting


class TestCostAccountant:
    def test_record_accumulates_and_derives_mfu(self):
        health = RuntimeHealth()
        acct = CostAccountant("cpu", peak=1e9, health=health)
        acct.register((8, 32), {"flops": 1e6, "bytes_accessed": 2e6,
                                "arithmetic_intensity": 0.5,
                                "cost_source": "xla"})
        acct.record((8, 32), device_ms=10.0, requests=8)
        acct.record((8, 32), device_ms=10.0, requests=8)
        snap = acct.snapshot()
        assert snap["device_ms"] == 20.0
        assert snap["device_calls"] == 2
        assert snap["requests"] == 16
        # 2 calls x 1e6 flops over 20ms of device time = 1e8 FLOP/s
        assert snap["achieved_flops_per_s"] == pytest.approx(1e8)
        assert snap["mfu"] == pytest.approx(0.1)
        exec_rec = snap["per_executable"]["b8w32"]
        assert exec_rec["cost_source"] == "xla"
        assert exec_rec["device_ms_per_request"] == pytest.approx(1.25)
        assert exec_rec["mfu"] == pytest.approx(0.1)
        gauges = health.snapshot()["gauges"]
        assert gauges["perf.mfu"] == pytest.approx(0.1)
        assert gauges["perf.peak_flops_per_s"] == 1e9
        assert gauges["perf.device_ms_total"] == 20.0
        assert 0.0 < gauges["perf.busy_fraction"] <= 1.0

    def test_unregistered_key_gets_time_but_no_flops(self):
        acct = CostAccountant("cpu", peak=1e9)
        acct.record((1, 8), device_ms=5.0)
        snap = acct.snapshot()
        assert snap["per_executable"]["b1w8"]["device_ms"] == 5.0
        assert snap["mfu"] is None  # no static cost -> no MFU claim

    def test_busy_fraction_and_mfu_bounded(self):
        # a fake clock that advances slower than recorded device time
        # would push busy over 1 — it must clamp
        t = [0.0]
        acct = CostAccountant("cpu", peak=1e9, clock=lambda: t[0])
        acct.register("k", {"flops": 10.0, "cost_source": "analytic"})
        t[0] = 0.001
        acct.record("k", device_ms=5.0)
        snap = acct.snapshot()
        assert snap["busy_fraction"] == 1.0

    def test_negative_device_ms_ignored(self):
        acct = CostAccountant("cpu")
        acct.record("k", device_ms=-1.0)
        assert acct.snapshot()["device_calls"] == 0


class TestFleetCapacity:
    def test_none_without_data(self):
        assert fleet_capacity([]) is None
        assert fleet_capacity([None, {}]) is None
        assert fleet_capacity([{"per_executable": {
            "b1w8": {"requests": 0, "device_ms": 0.0}}}]) is None

    def test_single_rung_math(self):
        perf = {"per_executable": {
            "b1w8": {"requests": 100, "device_ms": 200.0}}}
        cap = fleet_capacity([perf, perf])
        # 2ms/request -> 500 qps/replica, 2 alive -> 1000 fleet
        assert cap["alive_replicas"] == 2
        assert cap["device_ms_per_request"] == pytest.approx(2.0)
        assert cap["max_qps_per_replica"] == pytest.approx(500.0)
        assert cap["max_qps_fleet"] == pytest.approx(1000.0)
        (rung,) = cap["per_rung"]
        assert rung["rung"] == "b1w8"
        assert rung["share"] == 1.0

    def test_mix_weighted_harmonic(self):
        perf = {"per_executable": {
            # 75% of traffic at 1ms/req, 25% at 3ms/req
            "b1w8": {"requests": 75, "device_ms": 75.0},
            "b8w32": {"requests": 25, "device_ms": 75.0},
        }}
        cap = fleet_capacity([perf])
        # weighted: 0.75*1ms + 0.25*3ms = 1.5ms -> 666.67 qps
        assert cap["device_ms_per_request"] == pytest.approx(1.5)
        assert cap["max_qps_per_replica"] == pytest.approx(666.67, rel=1e-3)
        assert cap["max_qps_fleet"] == cap["max_qps_per_replica"]

    def test_dead_replicas_reduce_fleet_bound(self):
        perf = {"per_executable": {
            "b1w8": {"requests": 10, "device_ms": 10.0}}}
        cap = fleet_capacity([perf], alive=3)
        assert cap["max_qps_fleet"] == pytest.approx(3 * 1000.0)

    def test_garbage_entries_skipped(self):
        cap = fleet_capacity([{"per_executable": {
            "bad": {"requests": "x", "device_ms": "y"},
            "ok": {"requests": 4, "device_ms": 8.0},
        }}])
        assert cap["requests_observed"] == 4


# ---------------------------------------------------------------------------
# serve integration: a REAL compiled ladder


@pytest.fixture(scope="module")
def tiny_engine():
    import jax

    from code2vec_tpu.models.code2vec import Code2VecConfig
    from code2vec_tpu.serve.engine import ServingEngine
    from code2vec_tpu.train.config import TrainConfig
    from code2vec_tpu.train.step import create_train_state

    bag, embed, encode, labels = 16, 16, 24, 100
    config = TrainConfig(batch_size=4, max_path_length=bag)
    model_config = Code2VecConfig(
        terminal_count=200, path_count=200, label_count=labels,
        terminal_embed_size=embed, path_embed_size=embed,
        encode_size=encode, dropout_prob=0.0,
    )
    example = {
        "starts": np.zeros((1, bag), np.int32),
        "paths": np.zeros((1, bag), np.int32),
        "ends": np.zeros((1, bag), np.int32),
        "labels": np.zeros(1, np.int32),
        "example_mask": np.ones(1, np.float32),
    }
    state = create_train_state(
        config, model_config, jax.random.PRNGKey(0), example
    )
    health = RuntimeHealth()
    engine = ServingEngine(
        state, max_width=bag, model_dims=(embed, embed, encode),
        ladder=(8, 16), batch_sizes=(1, 4), health=health,
    )
    engine.prepare()
    return engine, health, model_config


class TestEngineCosts:
    def test_provenance_carries_cost_records(self, tiny_engine):
        engine, _, _ = tiny_engine
        assert engine.provenance  # (1,8),(4,8),(1,16),(4,16)
        for record in engine.provenance:
            cost = record["cost"]
            assert cost["cost_source"] in ("xla", "analytic")
            assert cost["flops"] > 0
            assert cost["arithmetic_intensity"] is None or (
                cost["arithmetic_intensity"] > 0
            )

    def test_xla_agrees_with_analytic_within_10pct(self, tiny_engine):
        # the tentpole acceptance bound, on a REAL compiled shape
        engine, _, mc = tiny_engine
        xla_seen = 0
        for record in engine.provenance:
            cost = record["cost"]
            if cost["cost_source"] != "xla":
                continue
            xla_seen += 1
            analytic = analytic_forward_cost(
                record["batch"], record["width"],
                terminal_embed=mc.terminal_embed_size,
                path_embed=mc.path_embed_size,
                encode=mc.encode_size,
                labels=mc.padded(mc.label_count),
            )
            assert cost["flops"] == pytest.approx(
                analytic["flops"], rel=0.10
            ), f"shape ({record['batch']}, {record['width']})"
        # CPU implements cost_analysis(); if this ever stops holding the
        # analytic fallback takes over and this test should be revisited
        assert xla_seen >= 1

    def test_device_time_folds_into_perf_summary(self, tiny_engine):
        engine, health, _ = tiny_engine
        before = (engine.perf_summary() or {}).get("device_calls", 0)
        starts = np.zeros((1, 8), np.int32)
        engine.run(starts, starts, starts)
        engine.record_device_time(1, 8, 2.5, requests=1)
        perf = engine.perf_summary()
        assert perf["device_calls"] == before + 1
        assert perf["per_executable"]["b1w8"]["device_ms"] >= 2.5
        # the acceptance invariant: achieved never exceeds peak
        assert perf["achieved_flops_per_s"] <= perf["peak_flops_per_s"]
        assert 0.0 < perf["mfu"] <= 1.0
        gauges = health.snapshot()["gauges"]
        assert gauges["perf.mfu"] == perf["mfu"]

    def test_batcher_feeds_device_time(self, tiny_engine):
        from code2vec_tpu.serve.batcher import MicroBatcher

        engine, _, _ = tiny_engine
        before = engine.perf_summary()["device_calls"]
        batcher = MicroBatcher(engine, deadline_ms=0.0)
        try:
            contexts = np.ones((5, 3), np.int32)
            batcher.submit(contexts).result(timeout=30.0)
        finally:
            batcher.close()
        perf = engine.perf_summary()
        assert perf["device_calls"] > before
        assert perf["per_executable"]["b1w8"]["requests"] >= 1

    def test_shape_miss_compile_also_gets_cost(self, tiny_engine):
        engine, _, _ = tiny_engine
        starts = np.zeros((2, 8), np.int32)  # batch 2 not in (1, 4)
        engine.run(starts, starts, starts)
        record = engine.provenance[-1]
        assert (record["batch"], record["width"]) == (2, 8)
        assert record["cost"]["cost_source"] in ("xla", "analytic")


# ---------------------------------------------------------------------------
# StepProfiler mfu column


class TestStepProfilerMfu:
    def test_mfu_column_when_flops_known(self):
        from code2vec_tpu.train.prefetch import StepProfiler

        prof = StepProfiler(sample_steps=4, peak_flops=1e9)
        prof.record_host(0, 1.0, 0.5)
        prof.record_compute(0, 10.0, flops=1e6)  # 1e8 FLOP/s -> mfu 0.1
        prof.record_compute(1, 10.0)  # no flops -> no mfu key
        steps = prof.per_step()
        assert steps[0]["mfu"] == pytest.approx(0.1)
        assert "mfu" not in steps[1]
        summary = prof.summary()
        assert summary["mfu"] == pytest.approx(0.1)
        assert summary["profiled_steps"] == 2

    def test_no_mfu_without_peak(self):
        from code2vec_tpu.train.prefetch import StepProfiler

        prof = StepProfiler(sample_steps=2)
        prof.record_compute(0, 10.0, flops=1e6)
        assert "mfu" not in prof.per_step()[0]
        assert "mfu" not in prof.summary()


# ---------------------------------------------------------------------------
# build_info gauge


class TestBuildInfo:
    def test_labels(self):
        info = build_info()
        assert info["package_version"]
        assert info["jax_version"] not in ("", None)
        assert info["python_version"].count(".") == 2

    def test_exposition_parses(self):
        text = build_info_text({"role": "router"})
        assert text.startswith("# TYPE c2v_build_info gauge\n")
        parsed = parse_prometheus_text(text)
        assert parsed["# types"]["c2v_build_info"] == "gauge"
        (sample,) = parsed["c2v_build_info"]
        assert sample["value"] == 1.0
        assert sample["labels"]["role"] == "router"
        assert "jax_version" in sample["labels"]


# ---------------------------------------------------------------------------
# the flights op (worker side; the router passthrough rides test_obsfleet)


def test_flights_op_returns_live_recorder_contents():
    from code2vec_tpu.obs.runtime import FlightRecorder
    from code2vec_tpu.serve.protocol import CodeServer

    flight = FlightRecorder(capacity=8, threshold_ms=0.0)
    flight.observe(12.5, {"kind": "serve", "op": "embed",
                          "e2e_ms": np.float64(12.5)})

    class _Batcher:
        def close(self, timeout=0.0):
            pass

    server = CodeServer(None, None, _Batcher(), flight=flight)
    resolver = server.handle_async({"op": "flights", "id": 7})
    payload = resolver()
    assert payload["id"] == 7
    assert payload["ok"] is True
    assert payload["recorded"] == 1
    assert payload["seen"] == 1
    (rec,) = payload["flights"]
    assert rec["op"] == "embed"
    json.dumps(payload)  # numpy scalars sanitized for the wire


def test_flights_op_without_recorder():
    from code2vec_tpu.serve.protocol import CodeServer

    class _Batcher:
        def close(self, timeout=0.0):
            pass

    server = CodeServer(None, None, _Batcher())
    payload = server.handle_async({"op": "flights"})()
    assert payload == {"ok": True, "recorded": 0, "seen": 0, "flights": []}


def test_flights_classified_as_health_slo_class():
    from code2vec_tpu.serve.fleet.slo import classify_op

    assert classify_op("flights") == "health"


# ---------------------------------------------------------------------------
# trace_stitch --trace-id


@pytest.fixture()
def stitched_trace_dir(tmp_path):
    router = tmp_path / "trace-p0.json"
    replica_dir = tmp_path / "r0"
    replica_dir.mkdir()
    replica = replica_dir / "trace-p0.json"
    router.write_text(json.dumps({"traceEvents": [
        {"name": "process_name", "ph": "M", "pid": 0,
         "args": {"name": "router"}},
        {"name": "fleet_request", "ph": "X", "pid": 0, "tid": 1,
         "ts": 1_000_000, "dur": 5000, "args": {"trace_id": "tid-1"}},
    ]}))
    replica.write_text(json.dumps({"traceEvents": [
        {"name": "process_name", "ph": "M", "pid": 0,
         "args": {"name": "worker"}},
        {"name": "serve_request", "ph": "X", "pid": 0, "tid": 1,
         "ts": 1_001_000, "dur": 3000, "args": {"trace_id": "tid-1"}},
        {"name": "serve_device", "ph": "X", "pid": 0, "tid": 2,
         "ts": 1_002_000, "dur": 1500,
         "args": {"trace_ids": ["tid-1", "tid-2"]}},
    ]}))
    return tmp_path


def test_critical_path_table_renders_per_hop_ms(stitched_trace_dir):
    paths = trace_stitch.find_trace_files([str(stitched_trace_dir)])
    index = trace_stitch.trace_index(trace_stitch.stitch_traces(paths))
    table = trace_stitch.critical_path_table("tid-1", index["tid-1"])
    lines = table.splitlines()
    assert "3 spans across 2 processes" in lines[0]
    assert "critical path 5.000 ms" in lines[0]
    body = "\n".join(lines)
    assert "fleet_request" in body
    assert "serve_device" in body
    assert "coalesced" in body
    assert "+1.000" in body  # serve_request starts 1ms after admission
    assert "5.000" in body  # fleet_request dur in ms


def test_trace_id_cli_prints_table_and_rejects_unknown(
    stitched_trace_dir, capsys
):
    trace_stitch.main([str(stitched_trace_dir), "--trace-id", "tid-1"])
    out = capsys.readouterr().out
    assert "trace tid-1" in out
    assert "serve_device" in out
    with pytest.raises(SystemExit, match="not found"):
        trace_stitch.main([str(stitched_trace_dir), "--trace-id", "nope"])


# ---------------------------------------------------------------------------
# perf_report sentinel exit codes


CLEAN = {
    "pad_efficiency": 0.26, "device_calls_per_request": 0.75,
    "post_warmup_recompiles": 0, "mfu": 0.001, "coalesce_mean": 1.6,
    "qps": 140.0,
}


def _bench_stream(tmp_path, name, **overrides):
    metrics = dict(CLEAN, **overrides)
    detail = {
        "mode": "serve",
        "pad_efficiency": metrics["pad_efficiency"],
        "post_warmup_recompiles": metrics["post_warmup_recompiles"],
        "coalesce_mean": metrics["coalesce_mean"],
        "completed": 100,
        "counters": {
            "serve_batches": int(metrics["device_calls_per_request"] * 100)
        },
        "qps": metrics["qps"],
        "latency_ms": {"e2e": {"p50_ms": 2.7, "p99_ms": 5.2}},
        "perf": {"mfu": metrics["mfu"], "busy_fraction": 0.02,
                 "device_kind": "cpu"},
    }
    path = tmp_path / name
    path.write_text(
        "some non-json log line\n"
        + json.dumps({"detail": detail}) + "\n"
        + json.dumps({"metric": "serve_requests_per_sec", "value": 140.0,
                      "mfu": metrics["mfu"]}) + "\n"
    )
    return str(path)


@pytest.fixture()
def baseline_file(tmp_path):
    current = _bench_stream(tmp_path, "base_stream.json")
    baseline = tmp_path / "baseline.json"
    rc = perf_report.main([
        "--update-baseline", "--baseline", str(baseline),
        "--current", current,
    ])
    assert rc == 0
    return str(baseline)


class TestPerfReportCheck:
    def test_clean_run_exits_zero(self, tmp_path, baseline_file, capsys):
        current = _bench_stream(tmp_path, "clean.json")
        rc = perf_report.main([
            "--check", "--baseline", baseline_file, "--current", current,
        ])
        assert rc == 0
        assert "perf sentinel: OK" in capsys.readouterr().out

    def test_small_noise_within_tolerance(self, tmp_path, baseline_file):
        current = _bench_stream(
            tmp_path, "noisy.json",
            pad_efficiency=CLEAN["pad_efficiency"] * 0.95,
            mfu=CLEAN["mfu"] * 0.5,  # hosts vary; only 10x decay fails
            coalesce_mean=CLEAN["coalesce_mean"] * 0.8,
        )
        assert perf_report.main([
            "--check", "--baseline", baseline_file, "--current", current,
        ]) == 0

    @pytest.mark.parametrize("regression", [
        {"pad_efficiency": 0.10},           # padding efficiency collapsed
        {"device_calls_per_request": 1.5},  # coalescing stopped working
        {"post_warmup_recompiles": 2},      # hot path recompiling
        {"mfu": 0.00005},                   # >10x MFU decay
        {"coalesce_mean": 0.5},             # batches fell apart
    ])
    def test_injected_regression_exits_nonzero(
        self, tmp_path, baseline_file, regression, capsys
    ):
        current = _bench_stream(tmp_path, "bad.json", **regression)
        rc = perf_report.main([
            "--check", "--baseline", baseline_file, "--current", current,
        ])
        assert rc == 1
        assert "PERF REGRESSION" in capsys.readouterr().err

    def test_mfu_above_one_violates_invariant(
        self, tmp_path, baseline_file, capsys
    ):
        current = _bench_stream(tmp_path, "impossible.json", mfu=1.5)
        rc = perf_report.main([
            "--check", "--baseline", baseline_file, "--current", current,
        ])
        assert rc == 1
        assert "invariant" in capsys.readouterr().err

    def test_metric_vanishing_fails_loudly(self, tmp_path, baseline_file):
        current = _bench_stream(tmp_path, "partial.json")
        data = [json.loads(l) for l in open(current) if l.startswith("{")]
        del data[0]["detail"]["pad_efficiency"]
        with open(current, "w") as f:
            for obj in data:
                f.write(json.dumps(obj) + "\n")
        assert perf_report.main([
            "--check", "--baseline", baseline_file, "--current", current,
        ]) == 1

    def test_empty_current_exits_2(self, tmp_path, baseline_file):
        empty = tmp_path / "empty.json"
        empty.write_text("no json here\n")
        assert perf_report.main([
            "--check", "--baseline", baseline_file,
            "--current", str(empty),
        ]) == 2

    def test_missing_baseline_exits_2(self, tmp_path):
        current = _bench_stream(tmp_path, "c.json")
        assert perf_report.main([
            "--check", "--baseline", str(tmp_path / "nope.json"),
            "--current", current,
        ]) == 2


def test_committed_baseline_is_loadable_and_gated():
    """The baseline the CI job checks against must stay well-formed."""
    path = os.path.join(os.path.dirname(__file__), "..", "tools",
                        "perf_baseline.json")
    with open(path, encoding="utf-8") as f:
        baseline = json.load(f)
    for gate in perf_report.GATES:
        assert gate in baseline, f"baseline lost gated metric {gate!r}"
    assert 0.0 < baseline["mfu"] <= 1.0
    assert baseline["post_warmup_recompiles"] == 0


def test_serve_metrics_reads_bench_stamp_format(tmp_path):
    """BENCH_rN.json stamps wrap the stream in {"raw": ..., "parsed": ...}."""
    inner = (
        json.dumps({"detail": {"mode": "serve", "pad_efficiency": 0.5,
                               "completed": 10,
                               "counters": {"serve_batches": 5},
                               "post_warmup_recompiles": 0,
                               "coalesce_mean": 2.0,
                               "perf": {"mfu": 0.01}}})
        + "\n" + json.dumps({"metric": "serve_requests_per_sec"})
    )
    stamp = tmp_path / "BENCH_r9.json"
    stamp.write_text(json.dumps({"raw": inner, "parsed": {"metric": "x"}}))
    metrics = perf_report.serve_metrics(perf_report.load_records(str(stamp)))
    assert metrics["pad_efficiency"] == 0.5
    assert metrics["device_calls_per_request"] == 0.5
    assert metrics["mfu"] == 0.01
