"""The async host input pipeline (train/prefetch.py): determinism vs the
synchronous path, exception propagation, backpressure, clean shutdown, and
the step-time attribution profiler.

The load-bearing property is bitwise equivalence: the prefetcher may only
*overlap* work, never change it — identical batches in identical order,
hence identical losses over an epoch under a fixed seed.
"""

import threading
import time

import numpy as np
import pytest

from code2vec_tpu.data.reader import load_corpus
from code2vec_tpu.data.synth import SPECS, generate_corpus_files
from code2vec_tpu.train.config import TrainConfig
from code2vec_tpu.train.loop import train
from code2vec_tpu.train.prefetch import (
    HostPrefetcher,
    StepProfiler,
    device_batches,
)


@pytest.fixture(scope="module")
def tiny(tmp_path_factory):
    out = tmp_path_factory.mktemp("tiny_prefetch")
    paths = generate_corpus_files(out, SPECS["tiny"])
    data = load_corpus(paths["corpus"], paths["path_idx"], paths["terminal_idx"])
    return data


TINY_CFG = dict(
    max_epoch=2,
    batch_size=32,
    encode_size=32,
    terminal_embed_size=16,
    path_embed_size=16,
    max_path_length=16,
    print_sample_cycle=0,
)


def _count_batches(n, batch=4, events=None):
    """A generator of n tiny dict batches that records production/cleanup."""
    produced = events if events is not None else []
    try:
        for i in range(n):
            produced.append(i)
            yield {"x": np.full(batch, i)}
    finally:
        produced.append("closed")


class TestOrderingAndDeterminism:
    def test_batch_order_identical_to_sync(self):
        ref = [b["x"].copy() for b in _count_batches(16)]
        with HostPrefetcher(_count_batches(16), lambda b: b, depth=2) as pf:
            got = [dev["x"] for _, dev in pf]
        assert len(got) == len(ref)
        for a, b in zip(ref, got):
            np.testing.assert_array_equal(a, b)

    def test_host_and_device_views_pair_up(self):
        to_device = lambda b: {k: v + 100 for k, v in b.items()}  # noqa: E731
        with HostPrefetcher(_count_batches(5), to_device, depth=2) as pf:
            for host, dev in pf:
                np.testing.assert_array_equal(host["x"] + 100, dev["x"])

    def test_epoch_losses_bitwise_match_sync(self, tiny):
        # the acceptance bar: a run with --prefetch_batches 2 produces the
        # identical batch order, hence bit-identical losses/F1, as the
        # synchronous path under the same seed
        r_sync = train(TrainConfig(**TINY_CFG), tiny)
        r_pref = train(TrainConfig(**TINY_CFG, prefetch_batches=2), tiny)
        assert len(r_sync.history) == len(r_pref.history)
        for a, b in zip(r_sync.history, r_pref.history):
            assert a["train_loss"] == b["train_loss"]
            assert a["test_loss"] == b["test_loss"]
            assert a["f1"] == b["f1"]

    def test_streaming_epochs_bitwise_match_sync(self, tiny):
        # the chunked java-large feed draws host RNG inside the producer
        # thread; order (and thus the draws) must still match exactly
        cfg = dict(TINY_CFG, stream_chunk_items=48, max_epoch=1)
        r_sync = train(TrainConfig(**cfg), tiny)
        r_pref = train(TrainConfig(**cfg, prefetch_batches=3), tiny)
        assert r_sync.history[0]["train_loss"] == r_pref.history[0]["train_loss"]
        assert r_sync.history[0]["f1"] == r_pref.history[0]["f1"]


class TestFailureAndShutdown:
    def test_producer_exception_propagates(self):
        def bad_batches():
            yield {"x": np.zeros(2)}
            yield {"x": np.zeros(2)}
            raise RuntimeError("corrupt corpus row")

        with HostPrefetcher(bad_batches(), lambda b: b, depth=2) as pf:
            it = iter(pf)
            next(it)
            next(it)
            with pytest.raises(RuntimeError, match="corrupt corpus row"):
                next(it)

    def test_to_device_exception_propagates(self):
        def exploding(batch):
            raise ValueError("bad sharding")

        with HostPrefetcher(_count_batches(3), exploding, depth=2) as pf:
            with pytest.raises(ValueError, match="bad sharding"):
                next(iter(pf))

    def test_yielded_none_is_not_end_of_stream(self):
        # a buggy builder yielding None must fail loudly in to_device,
        # not be mistaken for iterator exhaustion (silent truncation)
        def batches():
            yield {"x": np.zeros(2)}
            yield None

        def to_device(batch):
            return {k: v for k, v in batch.items()}

        with HostPrefetcher(batches(), to_device, depth=2) as pf:
            it = iter(pf)
            next(it)
            with pytest.raises(AttributeError):
                next(it)

    def test_bounded_queue_backpressure(self):
        events = []
        pf = HostPrefetcher(
            _count_batches(100, events=events), lambda b: b, depth=2
        )
        try:
            deadline = time.time() + 5.0
            # producer fills the queue (depth) + one in-flight item, then parks
            while time.time() < deadline and len(events) < 3:
                time.sleep(0.01)
            time.sleep(0.2)  # would overproduce here if unbounded
            assert 3 <= len(events) <= 4  # depth + in-flight (+/- park timing)
            consumed = sum(1 for _ in pf)
            assert consumed == 100
        finally:
            pf.close()

    def test_clean_shutdown_on_early_exit(self):
        events = []
        pf = HostPrefetcher(
            _count_batches(1000, events=events), lambda b: b, depth=2
        )
        next(iter(pf))  # consume one batch, then abandon the epoch
        pf.close()
        assert pf._thread.is_alive() is False
        # the generator's finally ran: no leaked iterator state
        assert events[-1] == "closed"
        # closed twice is a no-op
        pf.close()
        with pytest.raises(StopIteration):
            next(iter(pf))

    def test_exhausted_iterator_joins_thread(self):
        pf = HostPrefetcher(_count_batches(3), lambda b: b, depth=2)
        assert sum(1 for _ in pf) == 3
        assert pf._thread.is_alive() is False

    def test_depth_must_be_positive(self):
        with pytest.raises(ValueError, match="depth"):
            HostPrefetcher(_count_batches(1), lambda b: b, depth=0)

    def test_no_thread_leak_across_many_epochs(self):
        before = threading.active_count()
        for _ in range(8):
            with HostPrefetcher(_count_batches(4), lambda b: b, depth=2) as pf:
                for _ in pf:
                    pass
        assert threading.active_count() <= before + 1


class TestSyncTwin:
    def test_sync_path_yields_pairs_without_thread(self):
        before = threading.active_count()
        with device_batches(_count_batches(4), lambda b: b, prefetch=0) as st:
            got = [host["x"][0] for host, _ in st]
        assert got == [0, 1, 2, 3]
        assert threading.active_count() == before

    def test_sync_close_closes_generator(self):
        events = []
        with device_batches(
            _count_batches(100, events=events), lambda b: b, prefetch=0
        ) as st:
            next(iter(st))
        assert events[-1] == "closed"


class TestStepProfiler:
    def test_records_and_summary_keys(self):
        prof = StepProfiler(sample_steps=2)
        with device_batches(
            _count_batches(4), lambda b: b, prefetch=2, profiler=prof
        ) as st:
            for step, _ in enumerate(st):
                if prof.sampled(step):
                    prof.record_compute(step, 5.0)
        per_step = prof.per_step()
        assert [s["step"] for s in per_step] == [0, 1]
        for rec in per_step:
            assert {"host_build_ms", "h2d_ms", "compute_ms"} <= set(rec)
        summary = prof.summary()
        assert summary is not None
        assert summary["profiled_steps"] == 2
        assert summary["compute_ms"] == 5.0
        assert summary["host_build_ms"] >= 0.0
        assert summary["h2d_ms"] >= 0.0

    def test_unsampled_returns_none_summary(self):
        prof = StepProfiler(sample_steps=0)
        assert prof.sampled(0) is False
        assert prof.summary() is None
        assert prof.per_step() == []

    def test_reset_clears_records(self):
        prof = StepProfiler(sample_steps=1)
        prof.record_host(0, 1.0, 2.0)
        prof.record_compute(0, 3.0)
        prof.reset()
        assert prof.summary() is None

    def test_profiled_train_run_emits_attribution_metrics(self, tiny):
        cfg = TrainConfig(**dict(TINY_CFG, max_epoch=1), profile_steps=3)
        res = train(cfg, tiny)
        h = res.history[0]
        assert h["profiled_steps"] >= 1
        for key in ("host_build_ms", "h2d_ms", "compute_ms"):
            assert h[key] >= 0.0


class TestCliWiring:
    def test_flags_reach_config(self):
        from code2vec_tpu.cli import build_parser, config_from_args

        args = build_parser().parse_args(
            ["--prefetch_batches", "3", "--profile_steps", "5"]
        )
        config = config_from_args(args)
        assert config.prefetch_batches == 3
        assert config.profile_steps == 5

    def test_defaults_are_off(self):
        from code2vec_tpu.cli import build_parser, config_from_args

        config = config_from_args(build_parser().parse_args([]))
        assert config.prefetch_batches == 0
        assert config.profile_steps == 0
