"""Training-step parity + end-to-end integration (SURVEY.md §4 test plan)."""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from code2vec_tpu.data.reader import load_corpus
from code2vec_tpu.data.synth import SPECS, generate_corpus_files
from code2vec_tpu.formats.vectors_io import read_code_vectors
from code2vec_tpu.train.config import TrainConfig
from code2vec_tpu.train.loop import StopTraining, train
from code2vec_tpu.train.step import torch_style_adam, weighted_nll


@pytest.fixture(scope="module")
def tiny(tmp_path_factory):
    out = tmp_path_factory.mktemp("tiny_train")
    paths = generate_corpus_files(out, SPECS["tiny"])
    data = load_corpus(paths["corpus"], paths["path_idx"], paths["terminal_idx"])
    return paths, data


TINY_CFG = dict(
    max_epoch=4,
    batch_size=32,
    encode_size=64,
    terminal_embed_size=32,
    path_embed_size=32,
    max_path_length=32,
    print_sample_cycle=0,
)


class TestWeightedNLL:
    def test_matches_torch_nllloss_semantics(self):
        # weighted mean = sum(w_i * nll_i) / sum(w_i)
        rng = np.random.default_rng(0)
        logits = jnp.asarray(rng.normal(size=(5, 4)), jnp.float32)
        labels = jnp.asarray([0, 1, 2, 3, 1])
        w = jnp.asarray([1.0, 2.0, 0.5, 1.5])
        mask = jnp.ones(5)
        loss = weighted_nll(logits, labels, w, mask)
        logp = np.log(
            np.exp(logits) / np.exp(logits).sum(-1, keepdims=True)
        )
        nll = -logp[np.arange(5), np.asarray(labels)]
        wi = np.asarray(w)[np.asarray(labels)]
        expected = (nll * wi).sum() / wi.sum()
        assert float(loss) == pytest.approx(float(expected), rel=1e-5)

    def test_example_mask_excludes_rows(self):
        logits = jnp.asarray(np.random.default_rng(1).normal(size=(4, 3)), jnp.float32)
        labels = jnp.asarray([0, 1, 2, 0])
        w = jnp.ones(3)
        full = weighted_nll(logits[:2], labels[:2], w, jnp.ones(2))
        masked = weighted_nll(logits, labels, w, jnp.asarray([1.0, 1.0, 0.0, 0.0]))
        assert float(full) == pytest.approx(float(masked), rel=1e-6)


class TestTorchStyleAdam:
    def test_weight_decay_is_coupled_l2(self):
        # with zero gradient and nonzero weight decay, params must still move
        # toward zero through the adam moments (torch semantics), and the
        # first-step magnitude must match a hand-computed torch Adam step
        tx = torch_style_adam(lr=0.1, b1=0.9, b2=0.999, weight_decay=0.01)
        params = {"w": jnp.asarray([2.0])}
        state = tx.init(params)
        grads = {"w": jnp.asarray([0.0])}
        updates, _ = tx.update(grads, state, params)
        # effective grad = wd * w = 0.02; torch step1: m=0.002, v=4e-6*0.001..
        # just assert direction and nonzero
        assert float(updates["w"][0]) < 0.0

    def test_first_step_matches_torch_formula(self):
        lr, b1, b2, eps = 0.01, 0.9, 0.999, 1e-8
        g = 0.3
        tx = torch_style_adam(lr, b1, b2, weight_decay=0.0)
        params = {"w": jnp.asarray([1.0])}
        state = tx.init(params)
        updates, _ = tx.update({"w": jnp.asarray([g])}, state, params)
        # bias-corrected: mhat = g, vhat = g^2 -> step = -lr * g/(|g|+eps)
        expected = -lr * g / (np.sqrt(g * g) + eps)
        assert float(updates["w"][0]) == pytest.approx(expected, rel=1e-5)


class TestEndToEnd:
    def test_f1_rises_and_artifacts_written(self, tiny, tmp_path):
        paths, data = tiny
        out = tmp_path / "run"
        os.makedirs(out)
        cfg = TrainConfig(**TINY_CFG)
        res = train(
            cfg,
            data,
            out_dir=str(out),
            vectors_path=str(out / "code.vec"),
            test_result_path=str(out / "test_result.tsv"),
        )
        assert res.best_f1 > 0.5  # learnable synthetic signal
        labels, vectors = read_code_vectors(out / "code.vec")
        assert len(labels) == data.n_items
        assert vectors.shape == (data.n_items, cfg.encode_size)
        # test-result TSV has one row per test example
        rows = (out / "test_result.tsv").read_text().strip().split("\n")
        assert len(rows) == int(data.n_items * 0.2)
        fields = rows[0].split("\t")
        assert len(fields) == 5 and fields[1] in ("True", "False")

    def test_deterministic_given_seed(self, tiny):
        paths, data = tiny
        cfg = TrainConfig(**TINY_CFG).with_updates(max_epoch=2)
        r1 = train(cfg, data)
        r2 = train(cfg, data)
        assert r1.history[-1]["train_loss"] == pytest.approx(
            r2.history[-1]["train_loss"], rel=1e-5
        )
        assert r1.final_f1 == r2.final_f1

    def test_resume_from_checkpoint(self, tiny, tmp_path):
        paths, data = tiny
        out = tmp_path / "resume"
        os.makedirs(out)
        cfg = TrainConfig(**TINY_CFG).with_updates(max_epoch=2)
        first = train(cfg, data, out_dir=str(out))
        cfg2 = cfg.with_updates(max_epoch=4, resume=True)
        second = train(cfg2, data, out_dir=str(out))
        # resumed run continues from epoch 2, runs 2 more
        assert second.epochs_run <= 3
        assert second.best_f1 >= first.best_f1

    def test_task_flag_mismatch_rejected(self, tiny):
        paths, data = tiny  # loaded with infer_method only
        cfg = TrainConfig(**TINY_CFG).with_updates(infer_variable_name=True)
        with pytest.raises(ValueError, match="task flags disagree"):
            train(cfg, data)

    def test_report_fn_can_stop(self, tiny):
        paths, data = tiny
        cfg = TrainConfig(**TINY_CFG)
        calls = []

        def report(epoch, f1):
            calls.append(epoch)
            if epoch >= 1:
                raise StopTraining

        res = train(cfg, data, report_fn=report)
        assert calls == [0, 1]
        assert res.epochs_run == 2

    def test_variable_task_end_to_end(self, tiny_variable_corpus):
        data = tiny_variable_corpus
        cfg = TrainConfig(**TINY_CFG).with_updates(
            max_epoch=2, infer_variable_name=True
        )
        res = train(cfg, data)
        assert res.final_f1 >= 0.0
        assert len(res.history) == 2


@pytest.fixture(scope="module")
def tiny_variable_corpus(tmp_path_factory):
    out = tmp_path_factory.mktemp("tiny_var")
    paths = generate_corpus_files(out, SPECS["tiny"])
    return load_corpus(
        paths["corpus"],
        paths["path_idx"],
        paths["terminal_idx"],
        infer_method=True,
        infer_variable=True,
    )


class TestAngularMarginTraining:
    def test_margin_head_trains(self, tiny):
        paths, data = tiny
        cfg = TrainConfig(**TINY_CFG).with_updates(
            max_epoch=2, angular_margin_loss=True
        )
        res = train(cfg, data)
        assert np.isfinite(res.history[-1]["train_loss"])


class TestBf16Training:
    def test_bfloat16_compute_trains(self, tiny):
        paths, data = tiny
        cfg = TrainConfig(**TINY_CFG).with_updates(
            max_epoch=2, compute_dtype="bfloat16"
        )
        res = train(cfg, data)
        assert np.isfinite(res.history[-1]["train_loss"])
        assert res.final_f1 > 0.0
