"""Training-step parity + end-to-end integration (SURVEY.md §4 test plan)."""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from code2vec_tpu.data.reader import load_corpus
from code2vec_tpu.data.synth import SPECS, generate_corpus_files
from code2vec_tpu.formats.vectors_io import read_code_vectors
from code2vec_tpu.train.config import TrainConfig
from code2vec_tpu.train.loop import StopTraining, train
from code2vec_tpu.train.step import torch_style_adam, weighted_nll


@pytest.fixture(scope="module")
def tiny(tmp_path_factory):
    out = tmp_path_factory.mktemp("tiny_train")
    paths = generate_corpus_files(out, SPECS["tiny"])
    data = load_corpus(paths["corpus"], paths["path_idx"], paths["terminal_idx"])
    return paths, data


TINY_CFG = dict(
    max_epoch=4,
    batch_size=32,
    encode_size=64,
    terminal_embed_size=32,
    path_embed_size=32,
    max_path_length=32,
    print_sample_cycle=0,
)


class TestWeightedNLL:
    def test_matches_torch_nllloss_semantics(self):
        # weighted mean = sum(w_i * nll_i) / sum(w_i)
        rng = np.random.default_rng(0)
        logits = jnp.asarray(rng.normal(size=(5, 4)), jnp.float32)
        labels = jnp.asarray([0, 1, 2, 3, 1])
        w = jnp.asarray([1.0, 2.0, 0.5, 1.5])
        mask = jnp.ones(5)
        loss = weighted_nll(logits, labels, w, mask)
        logp = np.log(
            np.exp(logits) / np.exp(logits).sum(-1, keepdims=True)
        )
        nll = -logp[np.arange(5), np.asarray(labels)]
        wi = np.asarray(w)[np.asarray(labels)]
        expected = (nll * wi).sum() / wi.sum()
        assert float(loss) == pytest.approx(float(expected), rel=1e-5)

    def test_example_mask_excludes_rows(self):
        logits = jnp.asarray(np.random.default_rng(1).normal(size=(4, 3)), jnp.float32)
        labels = jnp.asarray([0, 1, 2, 0])
        w = jnp.ones(3)
        full = weighted_nll(logits[:2], labels[:2], w, jnp.ones(2))
        masked = weighted_nll(logits, labels, w, jnp.asarray([1.0, 1.0, 0.0, 0.0]))
        assert float(full) == pytest.approx(float(masked), rel=1e-6)


class TestTorchStyleAdam:
    def test_weight_decay_is_coupled_l2(self):
        # with zero gradient and nonzero weight decay, params must still move
        # toward zero through the adam moments (torch semantics), and the
        # first-step magnitude must match a hand-computed torch Adam step
        tx = torch_style_adam(lr=0.1, b1=0.9, b2=0.999, weight_decay=0.01)
        params = {"w": jnp.asarray([2.0])}
        state = tx.init(params)
        grads = {"w": jnp.asarray([0.0])}
        updates, _ = tx.update(grads, state, params)
        # effective grad = wd * w = 0.02; torch step1: m=0.002, v=4e-6*0.001..
        # just assert direction and nonzero
        assert float(updates["w"][0]) < 0.0

    def test_first_step_matches_torch_formula(self):
        lr, b1, b2, eps = 0.01, 0.9, 0.999, 1e-8
        g = 0.3
        tx = torch_style_adam(lr, b1, b2, weight_decay=0.0)
        params = {"w": jnp.asarray([1.0])}
        state = tx.init(params)
        updates, _ = tx.update({"w": jnp.asarray([g])}, state, params)
        # bias-corrected: mhat = g, vhat = g^2 -> step = -lr * g/(|g|+eps)
        expected = -lr * g / (np.sqrt(g * g) + eps)
        assert float(updates["w"][0]) == pytest.approx(expected, rel=1e-5)

    def test_bf16_mu_storage(self):
        # opt-in HBM lever: mu stored in bf16, nu stays f32, updates stay
        # close to the f32-moment step (one step: mhat = g exactly in both)
        lr, g = 0.01, 0.3
        tx = torch_style_adam(lr, 0.9, 0.999, 0.0, mu_dtype="bfloat16")
        params = {"w": jnp.asarray([1.0])}
        state = tx.init(params)
        adam_state = state[0] if isinstance(state, tuple) else state
        assert adam_state.mu["w"].dtype == jnp.bfloat16
        assert adam_state.nu["w"].dtype == jnp.float32
        updates, _ = tx.update({"w": jnp.asarray([g])}, state, params)
        expected = -lr * g / (np.sqrt(g * g) + 1e-8)
        assert float(updates["w"][0]) == pytest.approx(expected, rel=1e-2)

    def test_float32_mu_dtype_string_is_identity(self):
        tx = torch_style_adam(0.01, 0.9, 0.999, 0.0, mu_dtype="float32")
        state = tx.init({"w": jnp.asarray([1.0])})
        adam_state = state[0] if isinstance(state, tuple) else state
        assert adam_state.mu["w"].dtype == jnp.float32

    def test_bf16_mu_trains_end_to_end(self, tiny, tmp_path):
        # the flag threads through config -> create_train_state -> training;
        # bf16 moments must not break learning on the tiny corpus
        paths, data = tiny
        out = tmp_path / "mu16"
        os.makedirs(out)
        cfg = TrainConfig(**{**TINY_CFG, "max_epoch": 2}, adam_mu_dtype="bfloat16")
        res = train(cfg, data, out_dir=str(out))
        assert res.epochs_run == 2
        assert all(np.isfinite(h["train_loss"]) for h in res.history)
        assert res.best_f1 >= 0.0
        # the opt-in actually landed in the optimizer state
        mu = res.state.opt_state[0].mu if res.state is not None else None
        if mu is not None:
            leaf = jax.tree_util.tree_leaves(mu)[0]
            assert leaf.dtype == jnp.bfloat16

        # resume WITHOUT the flag: guidance, not a raw orbax dtype error
        cfg_wrong = TrainConfig(
            **{**TINY_CFG, "max_epoch": 3}, resume=True
        )
        with pytest.raises(ValueError, match="--adam_mu_dtype bfloat16"):
            train(cfg_wrong, data, out_dir=str(out))

        # resume WITH the flag round-trips
        cfg_resume = TrainConfig(
            **{**TINY_CFG, "max_epoch": 3},
            adam_mu_dtype="bfloat16", resume=True,
        )
        res2 = train(cfg_resume, data, out_dir=str(out))
        assert res2.epochs_run >= 1


class TestEndToEnd:
    def test_f1_rises_and_artifacts_written(self, tiny, tmp_path):
        paths, data = tiny
        out = tmp_path / "run"
        os.makedirs(out)
        cfg = TrainConfig(**TINY_CFG)
        res = train(
            cfg,
            data,
            out_dir=str(out),
            vectors_path=str(out / "code.vec"),
            test_result_path=str(out / "test_result.tsv"),
        )
        assert res.best_f1 > 0.5  # learnable synthetic signal
        labels, vectors = read_code_vectors(out / "code.vec")
        assert len(labels) == data.n_items
        assert vectors.shape == (data.n_items, cfg.encode_size)
        # test-result TSV has one row per test example
        rows = (out / "test_result.tsv").read_text().strip().split("\n")
        assert len(rows) == int(data.n_items * 0.2)
        fields = rows[0].split("\t")
        assert len(fields) == 5 and fields[1] in ("True", "False")

    def test_deterministic_given_seed(self, tiny):
        paths, data = tiny
        cfg = TrainConfig(**TINY_CFG).with_updates(max_epoch=2)
        r1 = train(cfg, data)
        r2 = train(cfg, data)
        assert r1.history[-1]["train_loss"] == pytest.approx(
            r2.history[-1]["train_loss"], rel=1e-5
        )
        assert r1.final_f1 == r2.final_f1

    def test_resume_from_checkpoint(self, tiny, tmp_path):
        paths, data = tiny
        out = tmp_path / "resume"
        os.makedirs(out)
        cfg = TrainConfig(**TINY_CFG).with_updates(max_epoch=2)
        first = train(cfg, data, out_dir=str(out))
        cfg2 = cfg.with_updates(max_epoch=4, resume=True)
        second = train(cfg2, data, out_dir=str(out))
        # resumed run continues from epoch 2, runs 2 more
        assert second.epochs_run <= 3
        assert second.best_f1 >= first.best_f1

    def test_periodic_checkpoint_cycle(self, tiny, tmp_path):
        # preemption safety: with checkpoint_cycle the meta on disk advances
        # every cycle even when F1 stops improving (best-F1-only would not)
        import json

        paths, data = tiny
        out = tmp_path / "cycle"
        os.makedirs(out)
        cfg = TrainConfig(**TINY_CFG).with_updates(
            max_epoch=4, checkpoint_cycle=1
        )
        train(cfg, data, out_dir=str(out))
        meta = json.loads((out / "train_meta.json").read_text())
        assert meta["epoch"] == 4  # saved after the final epoch, best or not
        # and the saved early-stop counters reflect the post-epoch state
        assert "bad_count" in meta and "last_loss" in meta

    def test_checkpoint_slots_coexist_and_fresh_run_clears(self, tiny, tmp_path):
        # slot mechanics at the API level (independent of the F1 trajectory):
        # a "last" save never prunes the "best" slot, restore picks the
        # newer of the two, and a fresh (non-resume) run clears both
        from code2vec_tpu.checkpoint import (
            TrainMeta, clear_checkpoints, restore_checkpoint, save_checkpoint,
        )
        from code2vec_tpu.models.code2vec import Code2VecConfig
        from code2vec_tpu.train.step import create_train_state
        from code2vec_tpu.data.pipeline import build_epoch, iter_batches

        paths, data = tiny
        cfg = TrainConfig(**TINY_CFG)
        mc = Code2VecConfig(
            terminal_count=len(data.terminal_vocab),
            path_count=len(data.path_vocab),
            label_count=len(data.label_vocab),
            terminal_embed_size=8, path_embed_size=8, encode_size=16,
        )
        rng = np.random.default_rng(0)
        epoch = build_epoch(data, np.arange(data.n_items), cfg.max_path_length, rng)
        batch = next(iter_batches(epoch, cfg.batch_size, rng=rng))
        state = create_train_state(cfg, mc, jax.random.PRNGKey(0), batch)

        out = tmp_path / "slots"
        os.makedirs(out)
        save_checkpoint(str(out), state, TrainMeta(epoch=1), slot="best")
        later = state.replace(step=state.step + 5)
        save_checkpoint(str(out), later, TrainMeta(epoch=3), slot="last")
        names = sorted(d.name for d in (out / "code2vec_ckpt").iterdir())
        assert names == ["last_5", "step_0"], names
        restored = restore_checkpoint(str(out), state)
        assert restored is not None
        new_state, meta = restored
        assert int(new_state.step) == 5 and meta.epoch == 3  # newer slot wins

        # the export path asks for the best-F1 slot even when a fresher
        # periodic "last" save exists (the meta sidecar is single-file and
        # tracks the newest save; only the restored arrays matter here)
        best_state, _ = restore_checkpoint(str(out), state, prefer_best=True)
        assert int(best_state.step) == 0

        clear_checkpoints(str(out))  # fresh-run reset: "last" slot only
        names = sorted(d.name for d in (out / "code2vec_ckpt").iterdir())
        assert names == ["step_0"], names  # best model survives
        restored = restore_checkpoint(str(out), state)
        assert restored is not None and int(restored[0].step) == 0

        # a newer best save prunes the superseded periodic save
        save_checkpoint(str(out), later, TrainMeta(epoch=3), slot="last")
        newest = state.replace(step=state.step + 9)
        save_checkpoint(str(out), newest, TrainMeta(epoch=4), slot="best")
        names = sorted(d.name for d in (out / "code2vec_ckpt").iterdir())
        assert names == ["step_9"], names

    def test_rng_impl_mismatch_rejected(self, tiny, tmp_path):
        paths, data = tiny
        out = tmp_path / "mismatch"
        os.makedirs(out)
        cfg = TrainConfig(**TINY_CFG).with_updates(max_epoch=1, rng_impl="rbg")
        train(cfg, data, out_dir=str(out))
        cfg2 = cfg.with_updates(
            max_epoch=2, resume=True, rng_impl="threefry2x32"
        )
        with pytest.raises(ValueError, match="--rng_impl rbg"):
            train(cfg2, data, out_dir=str(out))

    def test_empty_test_split_trains_and_exports(self, tmp_path, tmp_path_factory):
        """3 methods -> the 20% test split is empty; training and the
        best-F1 export must still complete (regression: np.concatenate
        of zero batches in export._forward_all)."""
        from code2vec_tpu.data.synth import SynthSpec

        src = tmp_path_factory.mktemp("tiny3")
        paths = generate_corpus_files(
            src, SynthSpec(n_methods=3, n_terminals=40, n_paths=30,
                           n_labels=3, mean_contexts=6.0, max_contexts=10,
                           seed=7),
        )
        data = load_corpus(paths["corpus"], paths["path_idx"], paths["terminal_idx"])
        out = tmp_path / "e3"
        os.makedirs(out)
        vectors = out / "code.vec"
        # 'exact' is the eval method that hard-errors in sklearn on empty
        # input — evaluate() must short-circuit to zeros
        cfg = TrainConfig(**TINY_CFG).with_updates(
            max_epoch=2, batch_size=2, eval_method="exact"
        )
        train(cfg, data, out_dir=str(out), vectors_path=str(vectors))
        labels, rows = read_code_vectors(str(vectors))
        assert len(labels) == 3 and rows.shape[0] == 3  # all rows are train rows

        # standalone export: same empty split, plus the requested TSV must
        # exist (zero rows) rather than silently never being written
        from code2vec_tpu.export import export_from_checkpoint

        tsv = out / "test_result.tsv"
        vectors.unlink()
        f1 = export_from_checkpoint(
            cfg, data, str(out), str(vectors), test_result_path=str(tsv)
        )
        assert f1 == 0.0
        assert vectors.exists() and tsv.exists() and tsv.read_text() == ""

    def test_export_from_checkpoint(self, tiny, tmp_path):
        """The standalone --export_only pass: restore and rewrite code.vec
        without training (the post-hoc export for sharded pod runs)."""
        from code2vec_tpu.export import export_from_checkpoint

        paths, data = tiny
        out = tmp_path / "exp"
        os.makedirs(out)
        cfg = TrainConfig(**TINY_CFG).with_updates(max_epoch=2)
        vectors = out / "code.vec"
        train(cfg, data, out_dir=str(out), vectors_path=str(vectors))
        first = vectors.read_text()
        vectors.unlink()
        f1 = export_from_checkpoint(cfg, data, str(out), str(vectors))
        assert vectors.exists()
        assert f1 >= 0.0
        # same header (rows x dims); vector bytes may differ only if the
        # best checkpoint predates the final epoch
        assert vectors.read_text().splitlines()[0] == first.splitlines()[0]

    def test_export_from_checkpoint_meshed(self, tiny, tmp_path):
        """Export honors the mesh config: a model_axis-sharded checkpoint
        restores sharded and exports through the parallel eval step."""
        from code2vec_tpu.export import export_from_checkpoint

        paths, data = tiny
        out = tmp_path / "expm"
        os.makedirs(out)
        cfg = TrainConfig(**TINY_CFG).with_updates(
            max_epoch=1, data_axis=2, model_axis=2
        )
        train(cfg, data, out_dir=str(out))
        vectors = out / "code.vec"
        f1 = export_from_checkpoint(cfg, data, str(out), str(vectors))
        assert vectors.exists() and f1 >= 0.0

    def test_vocab_pad_mismatch_rejected(self, tiny, tmp_path):
        """Resuming under a different model_axis (so a different implicit
        pad multiple, hence different table shapes) must fail with guidance,
        not an orbax shape error; pinning --vocab_pad_multiple resumes."""
        paths, data = tiny
        out = tmp_path / "padmismatch"
        os.makedirs(out)
        cfg = TrainConfig(**TINY_CFG).with_updates(max_epoch=1, model_axis=2)
        train(cfg, data, out_dir=str(out))
        cfg2 = cfg.with_updates(max_epoch=2, resume=True, model_axis=1)
        with pytest.raises(ValueError, match="--vocab_pad_multiple 2"):
            train(cfg2, data, out_dir=str(out))
        cfg3 = cfg2.with_updates(vocab_pad_multiple=2)
        result = train(cfg3, data, out_dir=str(out))
        assert result.epochs_run == 1  # epoch 0 restored, epoch 1 runs

    def test_rbg_rng_trains_and_resumes(self, tiny, tmp_path):
        # rbg dropout stream: trains, checkpoints, and restores (key-data
        # shape [4] differs from threefry's [2])
        paths, data = tiny
        out = tmp_path / "rbg"
        os.makedirs(out)
        cfg = TrainConfig(**TINY_CFG).with_updates(
            max_epoch=2, rng_impl="rbg"
        )
        first = train(cfg, data, out_dir=str(out))
        assert first.best_f1 >= 0.0
        cfg2 = cfg.with_updates(max_epoch=3, resume=True)
        second = train(cfg2, data, out_dir=str(out))
        assert second.epochs_run <= 2

    def test_task_flag_mismatch_rejected(self, tiny):
        paths, data = tiny  # loaded with infer_method only
        cfg = TrainConfig(**TINY_CFG).with_updates(infer_variable_name=True)
        with pytest.raises(ValueError, match="task flags disagree"):
            train(cfg, data)

    def test_report_fn_can_stop(self, tiny):
        paths, data = tiny
        cfg = TrainConfig(**TINY_CFG)
        calls = []

        def report(epoch, f1):
            calls.append(epoch)
            if epoch >= 1:
                raise StopTraining

        res = train(cfg, data, report_fn=report)
        assert calls == [0, 1]
        assert res.epochs_run == 2

    def test_variable_task_end_to_end(self, tiny_variable_corpus):
        data = tiny_variable_corpus
        cfg = TrainConfig(**TINY_CFG).with_updates(
            max_epoch=2, infer_variable_name=True
        )
        res = train(cfg, data)
        assert res.final_f1 >= 0.0
        assert len(res.history) == 2


@pytest.fixture(scope="module")
def tiny_variable_corpus(tmp_path_factory):
    out = tmp_path_factory.mktemp("tiny_var")
    paths = generate_corpus_files(out, SPECS["tiny"])
    return load_corpus(
        paths["corpus"],
        paths["path_idx"],
        paths["terminal_idx"],
        infer_method=True,
        infer_variable=True,
    )


class TestAngularMarginTraining:
    def test_margin_head_trains(self, tiny):
        paths, data = tiny
        cfg = TrainConfig(**TINY_CFG).with_updates(
            max_epoch=2, angular_margin_loss=True
        )
        res = train(cfg, data)
        assert np.isfinite(res.history[-1]["train_loss"])


class TestBf16Training:
    def test_bfloat16_compute_trains(self, tiny):
        paths, data = tiny
        cfg = TrainConfig(**TINY_CFG).with_updates(
            max_epoch=2, compute_dtype="bfloat16"
        )
        res = train(cfg, data)
        assert np.isfinite(res.history[-1]["train_loss"])
        assert res.final_f1 > 0.0
