"""Elastic-training suite: fault injection, async checkpointing, mid-epoch
resume, graceful preemption, and mesh-reshape restore.

Every recovery path here is exercised by a *scheduled* fault
(code2vec_tpu/faultinject.py) rather than by luck: a plan like
``train_step@9:raise`` deterministically crashes the 9th optimizer step, so
the assertions pin exact recovery semantics — most importantly that a
killed-and-resumed run reproduces the uninterrupted run's metric history
BITWISE (same mesh), and that a checkpoint written on one mesh shape
restores onto another.

Marked ``elastic``: the CI fault-injection smoke job runs
``pytest -m elastic``; the tests also run as part of tier-1.
"""

import json
import os
import signal
import subprocess
import sys

import jax
import numpy as np
import pytest

from code2vec_tpu import faultinject
from code2vec_tpu.data.reader import load_corpus
from code2vec_tpu.data.synth import SPECS, generate_corpus_files
from code2vec_tpu.train.config import TrainConfig
from code2vec_tpu.train.loop import train

pytestmark = pytest.mark.elastic

# metric keys that must round-trip bitwise through kill/resume
# (epoch_seconds is wall clock; pad_efficiency rides along when present)
METRIC_KEYS = ("train_loss", "test_loss", "accuracy", "precision", "recall", "f1")

TINY = dict(
    max_epoch=3,
    batch_size=32,
    encode_size=64,
    terminal_embed_size=32,
    path_embed_size=32,
    max_path_length=32,
    print_sample_cycle=0,
    checkpoint_cycle=1,
)
# the tiny corpus trains 5 steps/epoch at batch 32 — fault occurrences
# below stay under 15 total steps so every plan actually fires


@pytest.fixture(scope="module")
def tiny(tmp_path_factory):
    out = tmp_path_factory.mktemp("tiny_elastic")
    paths = generate_corpus_files(out, SPECS["tiny"])
    data = load_corpus(paths["corpus"], paths["path_idx"], paths["terminal_idx"])
    return paths, data


@pytest.fixture(autouse=True)
def _clear_fault_plan():
    """Each test starts and ends without an installed plan (train() also
    re-installs from its own config, but unit tests poke fault_point
    directly)."""
    faultinject.install_plan(None)
    yield
    faultinject.install_plan(None)


def assert_bitwise_history(r1, r2):
    assert len(r1.history) == len(r2.history), (
        [h["epoch"] for h in r1.history], [h["epoch"] for h in r2.history])
    for h1, h2 in zip(r1.history, r2.history):
        for key in METRIC_KEYS:
            assert h1[key] == h2[key], (h1["epoch"], key, h1[key], h2[key])


# ---------------------------------------------------------------------------
# fault-plan grammar + semantics
# ---------------------------------------------------------------------------


class TestFaultPlan:
    def test_parse_and_fire(self):
        plan = faultinject.parse_plan("p@2:raise,q:sleep1")
        plan.fire("p")  # occurrence 1: no action
        with pytest.raises(faultinject.FaultInjected):
            plan.fire("p")
        plan.fire("q")  # sleeps 1ms, returns
        assert plan.hits("p") == 2 and plan.hits("q") == 1

    @pytest.mark.parametrize("bad", [
        "p",              # no action
        "p:explode",      # unknown action
        "p@0:raise",      # occurrence < 1
        "p@1:raise,p:raise",  # duplicate clause (default occurrence is 1)
        ":raise",         # no point
        "p:sleep",        # sleep without millis
    ])
    def test_malformed_plans_rejected(self, bad):
        with pytest.raises(ValueError):
            faultinject.parse_plan(bad)

    def test_install_resets_counters(self):
        faultinject.install_plan("p@1:raise")
        with pytest.raises(faultinject.FaultInjected):
            faultinject.fault_point("p")
        faultinject.install_plan("p@1:raise")  # fresh counters
        with pytest.raises(faultinject.FaultInjected):
            faultinject.fault_point("p")
        faultinject.install_plan(None)
        faultinject.fault_point("p")  # no plan: no-op

    def test_env_install(self, monkeypatch):
        monkeypatch.setenv(faultinject.ENV_VAR, "envpoint@1:raise")
        plan = faultinject.install_plan_from_env()
        assert ("envpoint", 1) in plan.clauses

    def test_sigterm_action_sets_guard(self):
        from code2vec_tpu.train.preempt import (
            install_sigterm_handler, preemption_guard, restore_sigterm_handler,
        )
        previous = install_sigterm_handler()
        try:
            guard = preemption_guard()
            guard.clear()
            faultinject.install_plan("p@1:sigterm")
            faultinject.fault_point("p")
            signal.pthread_sigmask(signal.SIG_BLOCK, [])  # let it deliver
            assert guard.requested() and guard.reason == "SIGTERM"
        finally:
            restore_sigterm_handler(previous)
            preemption_guard().clear()


# ---------------------------------------------------------------------------
# checkpoint layer: atomicity, partial-save crash window, per-slot meta
# ---------------------------------------------------------------------------


def _small_state():
    from code2vec_tpu.models.code2vec import Code2VecConfig
    from code2vec_tpu.train.loop import dummy_batch
    from code2vec_tpu.train.step import create_train_state

    cfg = TrainConfig(batch_size=4, max_path_length=8, terminal_embed_size=8,
                      path_embed_size=8, encode_size=12)
    mc = Code2VecConfig(terminal_count=20, path_count=20, label_count=5,
                        terminal_embed_size=8, path_embed_size=8,
                        encode_size=12)
    return cfg, mc, create_train_state(
        cfg, mc, jax.random.PRNGKey(0), dummy_batch(cfg))


class TestCheckpointCrashWindows:
    def test_truncated_save_is_skipped_by_restore(self, tmp_path):
        """REGRESSION (crash window): restore used to pick the max-suffix
        dir unconditionally, so a save killed mid-write left a partial dir
        restore would select and die on. Now dirs missing orbax's commit
        marker are skipped with a warning."""
        import jax.numpy as jnp

        from code2vec_tpu.checkpoint import (
            _COMMIT_MARKERS, TrainMeta, restore_checkpoint, save_checkpoint,
        )

        _, _, state = _small_state()
        out = str(tmp_path)
        save_checkpoint(out, state, TrainMeta(epoch=1), slot="best")
        later = state.replace(step=jnp.asarray(7, jnp.int32))
        path = save_checkpoint(out, later, TrainMeta(epoch=2), slot="last")
        # simulate a crash mid-save: the commit marker never got written
        for marker in _COMMIT_MARKERS:
            marked = os.path.join(path, marker)
            if os.path.exists(marked):
                os.remove(marked)
        restored = restore_checkpoint(out, state)
        assert restored is not None
        assert restored.slot == "best" and restored.meta.epoch == 1
        assert int(restored.state.step) == 0

    def test_mid_save_fault_leaves_previous_checkpoint_restorable(self, tmp_path):
        """A save failing between the array write and the atomic publish
        leaves only a ``tmp.`` staging dir — never a selectable partial —
        and the previous checkpoint survives (pruning runs post-publish)."""
        import jax.numpy as jnp

        from code2vec_tpu.checkpoint import (
            CHECKPOINT_DIR, TrainMeta, restore_checkpoint, save_checkpoint,
        )

        _, _, state = _small_state()
        out = str(tmp_path)
        save_checkpoint(out, state, TrainMeta(epoch=1), slot="last")
        faultinject.install_plan("mid_save@1:raise")
        later = state.replace(step=jnp.asarray(9, jnp.int32))
        with pytest.raises(faultinject.FaultInjected):
            save_checkpoint(out, later, TrainMeta(epoch=2), slot="last")
        faultinject.install_plan(None)
        names = sorted(os.listdir(os.path.join(out, CHECKPOINT_DIR)))
        assert "last_0" in names  # previous save intact
        assert "last_9" not in names  # the failed save was never published
        restored = restore_checkpoint(out, state)
        assert restored is not None and int(restored.state.step) == 0
        # the NEXT save sweeps the stale staging dir and succeeds
        save_checkpoint(out, later, TrainMeta(epoch=2), slot="last")
        names = sorted(os.listdir(os.path.join(out, CHECKPOINT_DIR)))
        assert "last_9" in names
        assert not any(n.startswith("tmp.") for n in names)

    def test_per_slot_meta_matches_restored_arrays(self, tmp_path):
        """REGRESSION (documented quirk): the single top-level meta file
        belonged to the newest save of either slot, so a prefer_best
        restore could pair best-slot arrays with last-slot bookkeeping.
        Each slot dir now carries its own sidecar."""
        import jax.numpy as jnp

        from code2vec_tpu.checkpoint import (
            TrainMeta, restore_checkpoint, save_checkpoint,
        )

        _, _, state = _small_state()
        out = str(tmp_path)
        save_checkpoint(
            out, state, TrainMeta(epoch=1, best_f1=0.5), slot="best")
        later = state.replace(step=jnp.asarray(7, jnp.int32))
        save_checkpoint(
            out, later, TrainMeta(epoch=3, best_f1=0.5, bad_count=2),
            slot="last")
        best = restore_checkpoint(out, state, prefer_best=True)
        assert best.slot == "best"
        assert best.meta.epoch == 1 and best.meta.bad_count == 0
        newest = restore_checkpoint(out, state)
        assert newest.slot == "last"
        assert newest.meta.epoch == 3 and newest.meta.bad_count == 2

    def test_same_step_resave_never_unpublishes(self, tmp_path):
        """REGRESSION: a re-save at the SAME optimizer step (a preempted
        resume re-persisting what it restored) used to rmtree the
        published dir before replacing it — a kill between the two left
        NO restorable checkpoint. Same-step re-saves now refresh the
        sidecars in place; a fault mid-re-save leaves the dir complete
        with the old bookkeeping."""
        from code2vec_tpu.checkpoint import (
            TrainMeta, restore_checkpoint, save_checkpoint,
        )

        _, _, state = _small_state()
        out = str(tmp_path)
        save_checkpoint(out, state, TrainMeta(epoch=1), slot="last")
        faultinject.install_plan("mid_save@1:raise")
        with pytest.raises(faultinject.FaultInjected):
            save_checkpoint(out, state, TrainMeta(epoch=2), slot="last")
        faultinject.install_plan(None)
        survivor = restore_checkpoint(out, state)
        assert survivor is not None and survivor.meta.epoch == 1
        save_checkpoint(out, state, TrainMeta(epoch=2), slot="last")
        assert restore_checkpoint(out, state).meta.epoch == 2

    def test_cross_run_same_step_collision_overwrites_arrays(self, tmp_path):
        """The sidecar-only re-save must be limited to THIS run's own
        dirs: a complete checkpoint left by a PREVIOUS run at a colliding
        step (re-import into the same model_path, a retrain reaching the
        same best step) holds DIFFERENT arrays and must be fully
        overwritten, not sidecar-patched around."""
        from code2vec_tpu import checkpoint as ckpt_mod
        from code2vec_tpu.checkpoint import (
            TrainMeta, restore_checkpoint, save_checkpoint,
        )

        _, _, state = _small_state()
        out = str(tmp_path)
        save_checkpoint(out, state, TrainMeta(epoch=1), slot="best")
        ckpt_mod._SAME_RUN_PATHS.clear()  # simulate a new process run
        other = state.replace(
            params=jax.tree.map(lambda a: a + 1.0, state.params)
        )
        save_checkpoint(out, other, TrainMeta(epoch=5), slot="best")
        restored = restore_checkpoint(out, state, prefer_best=True)
        assert restored.meta.epoch == 5
        want = jax.tree_util.tree_leaves(other.params)[0]
        got = jax.tree_util.tree_leaves(restored.state.params)[0]
        assert np.array_equal(np.asarray(want), np.asarray(got))

    def test_clear_checkpoints_sweeps_staging_dirs(self, tmp_path):
        from code2vec_tpu.checkpoint import (
            CHECKPOINT_DIR, TrainMeta, clear_checkpoints, save_checkpoint,
        )

        _, _, state = _small_state()
        out = str(tmp_path)
        save_checkpoint(out, state, TrainMeta(), slot="best")
        base = os.path.join(out, CHECKPOINT_DIR)
        os.makedirs(os.path.join(base, "tmp.last_3"))
        clear_checkpoints(out)  # clears the last slot + staging leftovers
        names = sorted(os.listdir(base))
        assert names == ["step_0"]


# ---------------------------------------------------------------------------
# async checkpointing
# ---------------------------------------------------------------------------


class TestAsyncCheckpoint:
    def test_async_save_overlaps_persist(self, tmp_path):
        """save() must return while the persist still runs (the loop's next
        step overlaps the disk write), and finish() publishes it."""
        from code2vec_tpu.checkpoint import CheckpointWriter, TrainMeta

        _, _, state = _small_state()
        writer = CheckpointWriter(str(tmp_path), async_save=True)
        faultinject.install_plan("mid_save@1:sleep300")
        path = writer.save(state, TrainMeta(epoch=1), "last")
        in_flight = writer._thread is not None and writer._thread.is_alive()
        assert in_flight, "save() blocked until the persist completed"
        assert not os.path.exists(path)  # not yet published
        writer.finish()
        assert os.path.exists(path)

    def test_async_persist_failure_raises_at_next_save(self, tmp_path):
        from code2vec_tpu.checkpoint import CheckpointWriter, TrainMeta

        _, _, state = _small_state()
        writer = CheckpointWriter(str(tmp_path), async_save=True)
        faultinject.install_plan("mid_save@1:raise")
        writer.save(state, TrainMeta(epoch=1), "last")
        with pytest.raises(faultinject.FaultInjected):
            writer.save(state, TrainMeta(epoch=1), "last")
        writer.close()

    def test_async_at_most_one_in_flight(self, tmp_path):
        from code2vec_tpu.checkpoint import CheckpointWriter, TrainMeta

        _, _, state = _small_state()
        writer = CheckpointWriter(str(tmp_path), async_save=True)
        faultinject.install_plan("mid_save@1:sleep200")
        first = writer.save(state, TrainMeta(epoch=1), "last")
        # the second save must first wait out the first persist
        import jax.numpy as jnp

        second = writer.save(
            state.replace(step=jnp.asarray(1, jnp.int32)),
            TrainMeta(epoch=2), "last")
        assert os.path.exists(first) or os.path.exists(second)
        writer.finish()
        assert os.path.exists(second)

    def test_async_train_matches_sync_bitwise(self, tiny, tmp_path):
        """Acceptance: async overlap changes WHEN bytes hit disk, never the
        training trajectory — loss/metric parity with sync saves, and the
        checkpoint_save span splits into snapshot + persist phases."""
        from code2vec_tpu.obs.trace import Tracer

        _, data = tiny
        sync_dir, async_dir = str(tmp_path / "sync"), str(tmp_path / "async")
        r_sync = train(
            TrainConfig(**TINY, checkpoint_every_steps=2),
            data, out_dir=sync_dir, sinks=())
        tracer = Tracer()
        r_async = train(
            TrainConfig(**TINY, checkpoint_every_steps=2,
                        async_checkpoint=True),
            data, out_dir=async_dir, sinks=(), tracer=tracer)
        assert_bitwise_history(r_sync, r_async)
        names = [e["name"] for e in tracer.chrome_trace()["traceEvents"]
                 if e.get("ph") == "X"]
        assert "checkpoint_save.snapshot" in names
        assert "checkpoint_save.persist" in names
        # resuming from an async save works like any other
        r_resumed = train(
            TrainConfig(**TINY, resume=True), data, out_dir=async_dir,
            sinks=())
        assert r_resumed.best_f1 == r_async.best_f1


# ---------------------------------------------------------------------------
# mid-epoch kill -> resume -> bitwise-equal metrics
# ---------------------------------------------------------------------------


class TestMidEpochResume:
    def _kill_and_resume(self, data, out_dir, kill_cfg, resume_cfg):
        with pytest.raises(faultinject.FaultInjected):
            train(kill_cfg, data, out_dir=out_dir, sinks=())
        return train(resume_cfg, data, out_dir=out_dir, sinks=())

    def test_kill_mid_epoch_resume_bitwise(self, tiny, tmp_path):
        """THE acceptance test: fault-plan kill inside epoch 1, resume from
        the mid-epoch cursor, and the full metric history — including the
        interrupted epoch's train_loss — is bitwise that of an
        uninterrupted run."""
        _, data = tiny
        r_full = train(
            TrainConfig(**TINY), data, out_dir=str(tmp_path / "full"),
            sinks=())
        r_resumed = self._kill_and_resume(
            data, str(tmp_path / "killed"),
            TrainConfig(**TINY, checkpoint_every_steps=3,
                        fault_plan="train_step@9:raise"),
            TrainConfig(**TINY, resume=True),
        )
        assert_bitwise_history(r_full, r_resumed)

    def test_kill_mid_epoch_resume_bitwise_prefetch(self, tiny, tmp_path):
        """Same guarantee with the async input pipeline: the producer may
        have run ahead of the kill point, but the cursor records the
        CONSUMED position and the epoch-start RNG state, so the replay is
        unaffected by prefetch depth."""
        _, data = tiny
        r_full = train(
            TrainConfig(**TINY), data, out_dir=str(tmp_path / "full"),
            sinks=())
        r_resumed = self._kill_and_resume(
            data, str(tmp_path / "killed"),
            TrainConfig(**TINY, prefetch_batches=3, checkpoint_every_steps=2,
                        fault_plan="train_step@8:raise"),
            TrainConfig(**TINY, prefetch_batches=3, resume=True),
        )
        assert_bitwise_history(r_full, r_resumed)

    def test_kill_mid_epoch_resume_bitwise_bucketed(self, tiny, tmp_path):
        """Bucketed path: the cursor's per-bucket positions replay the
        seeded interleave to the exact batch."""
        _, data = tiny
        cfg = dict(TINY, bucketed=True, bucket_ladder="8,16,32")
        r_full = train(
            TrainConfig(**cfg), data, out_dir=str(tmp_path / "full"),
            sinks=())
        r_resumed = self._kill_and_resume(
            data, str(tmp_path / "killed"),
            TrainConfig(**cfg, checkpoint_every_steps=2,
                        fault_plan="train_step@9:raise"),
            TrainConfig(**cfg, resume=True),
        )
        assert_bitwise_history(r_full, r_resumed)

    def test_kill_in_prefetch_producer_resumes(self, tiny, tmp_path):
        """A fault in the producer THREAD propagates to the consumer, the
        run dies, and the last mid-epoch save still resumes bitwise."""
        _, data = tiny
        r_full = train(
            TrainConfig(**TINY), data, out_dir=str(tmp_path / "full"),
            sinks=())
        r_resumed = self._kill_and_resume(
            data, str(tmp_path / "killed"),
            TrainConfig(**TINY, prefetch_batches=2, checkpoint_every_steps=2,
                        fault_plan="prefetch_produce@9:raise"),
            TrainConfig(**TINY, resume=True),
        )
        assert_bitwise_history(r_full, r_resumed)

    def test_boundary_resume_is_also_bitwise(self, tiny, tmp_path):
        """Epoch-boundary cursors carry the next epoch's RNG start state,
        so even a plain epoch-granular resume now continues the stream
        bitwise (it used to restart the RNG from the seed)."""
        _, data = tiny
        r_full = train(
            TrainConfig(**TINY), data, out_dir=str(tmp_path / "full"),
            sinks=())
        out = str(tmp_path / "killed")
        with pytest.raises(faultinject.FaultInjected):
            # epoch_start@3 fires entering epoch 2 — after epoch 1's save
            train(TrainConfig(**TINY, fault_plan="epoch_start@3:raise"),
                  data, out_dir=out, sinks=())
        r_resumed = train(
            TrainConfig(**TINY, resume=True), data, out_dir=out, sinks=())
        assert_bitwise_history(r_full, r_resumed)

    def test_cursor_config_change_fails_with_guidance(self, tiny, tmp_path):
        """A mid-epoch cursor saved under one batching config cannot be
        replayed under another — fail loudly, not silently wrong."""
        _, data = tiny
        out = str(tmp_path / "killed")
        with pytest.raises(faultinject.FaultInjected):
            train(TrainConfig(**TINY, checkpoint_every_steps=2,
                              fault_plan="train_step@8:raise"),
                  data, out_dir=out, sinks=())
        with pytest.raises(ValueError, match="cursor|changed since"):
            train(TrainConfig(**dict(TINY, batch_size=16), resume=True),
                  data, out_dir=out, sinks=())

    def test_checkpoint_restored_event(self, tiny, tmp_path):
        from code2vec_tpu.obs.events import EventLog

        _, data = tiny
        out = str(tmp_path / "run")
        with pytest.raises(faultinject.FaultInjected):
            train(TrainConfig(**TINY, checkpoint_every_steps=2,
                              fault_plan="train_step@8:raise"),
                  data, out_dir=out, sinks=())
        events = EventLog()
        seen = []
        events.subscribe(seen.append)
        train(TrainConfig(**TINY, resume=True), data, out_dir=out, sinks=(),
              events=events)
        restored = [e for e in seen if e["event"] == "checkpoint_restored"]
        assert len(restored) == 1
        event = restored[0]
        assert event["slot"] == "last"
        # the dir itself is pruned by the resumed run's later saves; the
        # event records provenance, not a live path
        assert os.path.basename(event["path"]).startswith("last_")
        # fault fired at global step 8; the last mid-epoch save (every 2
        # epoch-steps, 5 steps/epoch) landed after global step 7
        assert event["step"] == 7
        assert event["resharded"] is False
        assert event["mesh_shape"] is None
        saved = [e for e in seen if e["event"] == "checkpoint_saved"]
        assert saved and all("slot" in e and "path" in e for e in saved)


# ---------------------------------------------------------------------------
# graceful preemption (SIGTERM contract)
# ---------------------------------------------------------------------------


class TestGracefulPreemption:
    def test_sigterm_saves_and_exits_cleanly_then_resumes_bitwise(
            self, tiny, tmp_path):
        """SIGTERM mid-epoch: the in-flight step finishes, a cursor-bearing
        last-slot save lands, train() RETURNS (exit code 0), and the resume
        is bitwise."""
        _, data = tiny
        r_full = train(
            TrainConfig(**TINY), data, out_dir=str(tmp_path / "full"),
            sinks=())
        out = str(tmp_path / "preempted")
        r_pre = train(
            TrainConfig(**TINY, fault_plan="train_step@8:sigterm"),
            data, out_dir=out, sinks=())
        assert r_pre.epochs_run == 1  # epoch 1 was interrupted, not counted
        from code2vec_tpu.checkpoint import CHECKPOINT_DIR

        names = sorted(os.listdir(os.path.join(out, CHECKPOINT_DIR)))
        assert any(n.startswith("last_") for n in names), names
        r_resumed = train(
            TrainConfig(**TINY, resume=True), data, out_dir=out, sinks=())
        assert_bitwise_history(r_full, r_resumed)

    def test_sigterm_during_resume_setup_preserves_pending_cursor(
            self, tiny, tmp_path):
        """REGRESSION: SIGTERM landing on a resumed run BEFORE its first
        epoch consumed the mid-epoch cursor (the restore/setup window)
        used to overwrite the pending cursor with a step-0 boundary
        cursor while the state held mid-epoch arrays — the next resume
        then replayed the epoch head on top of them. The pending cursor
        must be re-persisted as-is."""
        _, data = tiny
        r_full = train(
            TrainConfig(**TINY), data, out_dir=str(tmp_path / "full"),
            sinks=())
        out = str(tmp_path / "killed")
        with pytest.raises(faultinject.FaultInjected):
            train(TrainConfig(**TINY, checkpoint_every_steps=3,
                              fault_plan="train_step@9:raise"),
                  data, out_dir=out, sinks=())
        # resume attempt 1: preempted at the very first epoch_start,
        # before the cursor was consumed — exits cleanly, re-saving it
        train(TrainConfig(**TINY, resume=True,
                          fault_plan="epoch_start@1:sigterm"),
              data, out_dir=out, sinks=())
        # resume attempt 2 completes bitwise from the preserved cursor
        r_resumed = train(
            TrainConfig(**TINY, resume=True), data, out_dir=out, sinks=())
        assert_bitwise_history(r_full, r_resumed)

    def test_drain_is_train_stream_only(self):
        """REGRESSION: the producer drain once applied to EVAL streams
        too — a SIGTERM during eval truncated the test set and recorded
        partial metrics as a completed epoch. Only train streams
        (drain_on_preemption=True) may end early on the guard; the
        consumer hook re-checks at stream end and never records them."""
        import numpy as np

        from code2vec_tpu.train.preempt import preemption_guard
        from code2vec_tpu.train.prefetch import device_batches

        def batches(n=6):
            for i in range(n):
                yield {"paths": np.full((2, 4), i, np.int32)}

        guard = preemption_guard()
        guard.request("SIGTERM")
        try:
            with device_batches(
                batches(), lambda b: b, prefetch=2
            ) as stream:  # eval default: runs to completion
                assert len(list(stream)) == 6
            with device_batches(
                batches(), lambda b: b, prefetch=2,
                drain_on_preemption=True,
            ) as stream:  # train: drains early
                assert len(list(stream)) < 6
        finally:
            guard.clear()

    def test_sigterm_with_prefetch_producer_drains(self, tiny, tmp_path):
        """The producer thread polls the same guard: it stops building
        batches and ends the stream instead of racing the shutdown."""
        _, data = tiny
        r_full = train(
            TrainConfig(**TINY), data, out_dir=str(tmp_path / "full"),
            sinks=())
        out = str(tmp_path / "preempted")
        r_pre = train(
            TrainConfig(**TINY, prefetch_batches=3,
                        fault_plan="train_step@8:sigterm"),
            data, out_dir=out, sinks=())
        assert r_pre.epochs_run == 1
        r_resumed = train(
            TrainConfig(**TINY, prefetch_batches=3, resume=True),
            data, out_dir=out, sinks=())
        assert_bitwise_history(r_full, r_resumed)


# ---------------------------------------------------------------------------
# mesh-reshape restore
# ---------------------------------------------------------------------------

MESH = dict(TINY, vocab_pad_multiple=4)


@pytest.mark.skipif(jax.device_count() < 4, reason="needs 4 CPU devices")
class TestMeshReshape:
    def test_validate_runtime_spec(self):
        from code2vec_tpu.analysis.sharding_check import validate_runtime_spec

        ok = validate_runtime_spec(["data", None], {"data", "model"})
        assert ok == []
        bad = validate_runtime_spec(
            ["gone", ["data", "data"]], {"data", "model"})
        assert any("SC001" in p for p in bad)
        assert any("SC002" in p for p in bad)

    def test_reshape_restore_param_parity(self, tmp_path):
        """Save on a 2x2 mesh, restore on 1x4: every leaf bitwise-equal,
        shardings re-bound to the new mesh."""
        from jax.sharding import NamedSharding

        from code2vec_tpu.checkpoint import (
            TrainMeta, restore_checkpoint, save_checkpoint,
        )
        from code2vec_tpu.parallel.mesh import make_mesh
        from code2vec_tpu.parallel.shardings import shard_state
        from code2vec_tpu.models.code2vec import Code2VecConfig
        from code2vec_tpu.train.loop import dummy_batch
        from code2vec_tpu.train.step import create_train_state

        cfg = TrainConfig(batch_size=4, max_path_length=8,
                          terminal_embed_size=8, path_embed_size=8,
                          encode_size=12, vocab_pad_multiple=4)
        mc = Code2VecConfig(terminal_count=20, path_count=20, label_count=5,
                            terminal_embed_size=8, path_embed_size=8,
                            encode_size=12, vocab_pad_multiple=4)
        state = create_train_state(cfg, mc, jax.random.PRNGKey(0),
                                   dummy_batch(cfg))
        mesh_a = make_mesh(data=2, model=2, ctx=1)
        state_a = shard_state(mesh_a, state)
        save_checkpoint(str(tmp_path), state_a, TrainMeta(epoch=1))
        mesh_b = make_mesh(data=1, model=4, ctx=1)
        restored = restore_checkpoint(
            str(tmp_path), shard_state(mesh_b, state), mesh=mesh_b)
        assert restored.resharded
        assert restored.saved_mesh_shape == {"data": 2, "model": 2, "ctx": 1}
        for (pa, la), (pb, lb) in zip(
            jax.tree_util.tree_leaves_with_path(state_a.params),
            jax.tree_util.tree_leaves_with_path(restored.state.params),
        ):
            assert pa == pb
            assert np.array_equal(jax.device_get(la), jax.device_get(lb)), pa
            assert isinstance(lb.sharding, NamedSharding)
            assert dict(lb.sharding.mesh.shape) == {
                "data": 1, "model": 4, "ctx": 1}

    def test_reshape_restore_rejects_unknown_axis(self, tmp_path):
        """A checkpoint whose specs name axes the restore mesh does not
        declare fails with sharding_check guidance, not a late XLA error."""
        from code2vec_tpu.checkpoint import (
            SHARDINGS_FILE, TrainMeta, restore_checkpoint, save_checkpoint,
        )
        from code2vec_tpu.parallel.mesh import make_mesh
        from code2vec_tpu.parallel.shardings import shard_state

        _, _, state = _small_state()
        mesh = make_mesh(data=2, model=2, ctx=1)
        cfg, mc, _ = _small_state()
        path = save_checkpoint(
            str(tmp_path), shard_state(mesh, state), TrainMeta())
        doc_path = os.path.join(path, SHARDINGS_FILE)
        with open(doc_path) as f:
            doc = json.load(f)
        for key, entries in doc["specs"].items():
            if entries:
                doc["specs"][key] = ["bogus_axis"] + entries[1:]
                break
        else:
            pytest.skip("no sharded leaf recorded")
        with open(doc_path, "w") as f:
            json.dump(doc, f)
        with pytest.raises(ValueError, match="bogus_axis"):
            restore_checkpoint(
                str(tmp_path), shard_state(mesh, state), mesh=mesh)

    def test_same_mesh_mid_epoch_resume_bitwise(self, tiny, tmp_path):
        """Kill mid-epoch on a 2x2 mesh, resume on the SAME mesh: fully
        bitwise — the strict form of the acceptance criterion."""
        _, data = tiny
        cfg = dict(MESH, data_axis=2, model_axis=2)
        r_full = train(TrainConfig(**cfg), data,
                       out_dir=str(tmp_path / "full"), sinks=())
        out = str(tmp_path / "killed")
        with pytest.raises(faultinject.FaultInjected):
            train(TrainConfig(**cfg, checkpoint_every_steps=2,
                              fault_plan="train_step@8:raise"),
                  data, out_dir=out, sinks=())
        r_resumed = train(TrainConfig(**cfg, resume=True), data,
                          out_dir=out, sinks=())
        assert_bitwise_history(r_full, r_resumed)

    def test_reshape_mid_epoch_resume(self, tiny, tmp_path):
        """Kill mid-epoch on 2x2, resume on 1x4: the restored model's eval
        metrics are bitwise-equal across the reshape (same params, same
        predictions), and the CONTINUED training tracks the uninterrupted
        run to float tolerance. Continuation cannot be bitwise across a
        topology change: a 4-way collective reduction associates partial
        sums differently than a 2-way one, which is float-semantics, not
        checkpoint state — the bitwise form of the criterion is pinned by
        test_same_mesh_mid_epoch_resume_bitwise above.
        """
        from code2vec_tpu.export import export_from_checkpoint

        _, data = tiny
        r_full = train(
            TrainConfig(**MESH, data_axis=2, model_axis=2), data,
            out_dir=str(tmp_path / "full"), sinks=())
        out = str(tmp_path / "killed")
        with pytest.raises(faultinject.FaultInjected):
            train(TrainConfig(**MESH, data_axis=2, model_axis=2,
                              checkpoint_every_steps=2,
                              fault_plan="train_step@8:raise"),
                  data, out_dir=out, sinks=())
        # the restored checkpoint evaluates IDENTICALLY on 2x2, 1x4, and a
        # single device: prediction-derived metrics are reduction-order-free
        f1_22 = export_from_checkpoint(
            TrainConfig(**MESH, data_axis=2, model_axis=2), data, out,
            str(tmp_path / "a.vec"))
        f1_14 = export_from_checkpoint(
            TrainConfig(**MESH, data_axis=1, model_axis=4), data, out,
            str(tmp_path / "b.vec"))
        assert f1_14 == f1_22
        # resumed training on the new topology completes and stays close
        r_resumed = train(
            TrainConfig(**MESH, data_axis=1, model_axis=4, resume=True),
            data, out_dir=out, sinks=())
        assert len(r_resumed.history) == len(r_full.history)
        # epoch 1 finished on 2x2 before the kill and rides in through the
        # checkpoint's history: bitwise. Post-reshape epochs continue on
        # 1x4, where reduction-order drift compounds step over step on
        # this tiny corpus — hence the loose tolerance.
        for key in METRIC_KEYS:
            assert r_full.history[0][key] == r_resumed.history[0][key], key
        for h1, h2 in zip(r_full.history[1:], r_resumed.history[1:]):
            assert h1["train_loss"] == pytest.approx(
                h2["train_loss"], rel=0.2)
            assert h1["f1"] == pytest.approx(h2["f1"], abs=0.15)


# ---------------------------------------------------------------------------
# skip_batches (the replay primitive)
# ---------------------------------------------------------------------------


class TestSkipBatches:
    def _stream(self, n=5, width=8):
        for i in range(n):
            yield {"paths": np.full((2, width), i, np.int32)}

    def test_skips_exactly_n(self):
        from code2vec_tpu.data.pipeline import skip_batches

        rest = list(skip_batches(self._stream(), 2))
        assert [int(b["paths"][0, 0]) for b in rest] == [2, 3, 4]

    def test_past_end_raises_with_guidance(self):
        from code2vec_tpu.data.pipeline import skip_batches

        with pytest.raises(ValueError, match="changed since"):
            skip_batches(self._stream(n=3), 5)

    def test_width_mismatch_raises(self):
        from code2vec_tpu.data.pipeline import skip_batches

        with pytest.raises(ValueError, match="bucket"):
            skip_batches(self._stream(width=8), 2, expect_widths={"16": 2})

    def test_width_match_accepted(self):
        from code2vec_tpu.data.pipeline import skip_batches

        rest = list(skip_batches(self._stream(), 3, expect_widths={8: 3}))
        assert len(rest) == 2


# ---------------------------------------------------------------------------
# SIGKILL smoke (subprocess): the CI fault-injection job's core scenario
# ---------------------------------------------------------------------------


# ---------------------------------------------------------------------------
# multiprocess harness: fault-kill a 2-process group, resume it reshaped
# ---------------------------------------------------------------------------

_MP_WORKER = os.path.join(os.path.dirname(__file__), "mp_worker.py")
_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _spawn_mp_group(tmp_path, n_procs, extra_env, expect_failure=False):
    """Minimal test_multiprocess.py::_run_group variant that tolerates the
    expected fault-plan death. Returns {process_index: result_json} on
    success, or the concatenated worker logs when expect_failure."""
    import socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    procs = []
    for pid in range(n_procs):
        env = os.environ.copy()
        env.update(
            COORDINATOR_ADDRESS=f"127.0.0.1:{port}",
            NUM_PROCESSES=str(n_procs),
            PROCESS_ID=str(pid),
            PYTHONPATH=_REPO,
            **extra_env,
        )
        env.pop("XLA_FLAGS", None)  # the worker pins its own
        ds = tmp_path / f"ds{pid}"
        ds.mkdir(exist_ok=True)
        (tmp_path / "out").mkdir(exist_ok=True)
        log = open(tmp_path / f"worker{pid}.log", "w+", encoding="utf-8")
        procs.append((
            subprocess.Popen(
                [sys.executable, _MP_WORKER, str(ds), str(tmp_path / "out")],
                stdout=log, stderr=subprocess.STDOUT, cwd=_REPO, env=env,
            ),
            log,
        ))
    try:
        for p, _ in procs:
            try:
                p.wait(timeout=600)
            except subprocess.TimeoutExpired:
                pass
    finally:
        for p, _ in procs:
            if p.poll() is None:
                p.kill()
                p.wait()
    results, logs = {}, []
    for p, log in procs:
        log.flush()
        log.seek(0)
        out = log.read()
        log.close()
        logs.append(out)
        if "Multiprocess computations aren't implemented" in out:
            # this jaxlib's CPU backend has no multiprocess collectives
            # (the same environmental limit the test_multiprocess.py suite
            # hits); the harness is exercised where the backend supports it
            pytest.skip("CPU backend lacks multiprocess collectives")
        if expect_failure:
            assert p.returncode != 0, f"worker survived its fault plan:\n{out[-2000:]}"
            continue
        assert p.returncode == 0, f"worker failed:\n{out[-4000:]}"
        last = [ln for ln in out.splitlines() if ln.startswith("{")][-1]
        r = json.loads(last)
        results[r["process"]] = r
    return "\n".join(logs) if expect_failure else results


@pytest.mark.slow
def test_multiprocess_fault_kill_then_reshaped_group_resume(tmp_path):
    """The acceptance scenario on the REAL multiprocess harness: a
    2-process jax.distributed group (4 global devices, mesh data=4) dies
    from a scheduled fault mid-epoch-2; the group restarts with a
    DIFFERENT mesh (data=2 x model=2 — the tables/head now sharded over a
    model axis that did not exist at save time), restores the collective
    orbax checkpoint, and completes in lockstep."""
    common = dict(MP_CHECKPOINT_CYCLE="1", MP_VOCAB_PAD="2")
    logs = _spawn_mp_group(
        tmp_path, 2,
        dict(common, C2V_FAULT_PLAN="train_step@8:raise"),
        expect_failure=True,
    )
    assert "FaultInjected" in logs
    from code2vec_tpu.checkpoint import CHECKPOINT_DIR

    names = os.listdir(tmp_path / "out" / CHECKPOINT_DIR)
    assert any(n.startswith(("step_", "last_")) for n in names), names
    results = _spawn_mp_group(
        tmp_path, 2,
        dict(common, MP_RESUME="1", MP_DATA_AXIS="2", MP_MODEL_AXIS="2"),
    )
    assert set(results) == {0, 1}
    # lockstep: both processes observe the same global computation
    assert results[0]["losses"] == results[1]["losses"]
    assert results[0]["f1s"] == results[1]["f1s"]
    # epoch 1 rides in from the killed run's checkpoint; 2-3 run reshaped
    assert len(results[0]["losses"]) == 3
    assert results[0]["best_f1"] > 0


_KILL_SCRIPT = """
import sys
from code2vec_tpu.cli import main
main(sys.argv[1:])
"""


@pytest.mark.usefixtures("zero_leaked_handles")
def test_sigkill_mid_epoch_then_cli_resume(tiny, tmp_path):
    """The unceremonious preemption: SIGKILL mid-epoch through the real
    CLI (no finally blocks, no atexit — recovery works from what reached
    disk), then ``--resume`` completes the run. Exit code must be -SIGKILL,
    proving the fault fired rather than the run finishing early."""
    paths, _ = tiny
    out = str(tmp_path / "model")
    argv = [
        "--corpus_path", paths["corpus"],
        "--path_idx_path", paths["path_idx"],
        "--terminal_idx_path", paths["terminal_idx"],
        "--model_path", out,
        "--vectors_path", str(tmp_path / "code.vec"),
        "--max_epoch", "2", "--batch_size", "32", "--encode_size", "64",
        "--terminal_embed_size", "32", "--path_embed_size", "32",
        "--max_path_length", "32", "--print_sample_cycle", "0",
        "--checkpoint_every_steps", "2", "--no_cuda",
    ]
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    # occurrence 9 = epoch 2, step 4 of 5: AFTER epoch 2's first periodic
    # save (last_7) — an earlier kill would leave only the epoch-1
    # boundary save (`step_5` prunes the last slot it supersedes)
    proc = subprocess.run(
        [sys.executable, "-c", _KILL_SCRIPT]
        + argv + ["--fault_plan", "train_step@9:kill"],
        env=env, capture_output=True, text=True, timeout=300,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    )
    assert proc.returncode == -signal.SIGKILL, proc.stdout + proc.stderr
    from code2vec_tpu.checkpoint import CHECKPOINT_DIR

    assert any(
        n.startswith("last_")
        for n in os.listdir(os.path.join(out, CHECKPOINT_DIR))
    )
    proc = subprocess.run(
        [sys.executable, "-c", _KILL_SCRIPT] + argv + ["--resume"],
        env=env, capture_output=True, text=True, timeout=300,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "done: best_f1=" in proc.stderr + proc.stdout
