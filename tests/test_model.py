"""Model semantics tests (reference parity: model/model.py — SURVEY.md §2.2)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from code2vec_tpu.models.code2vec import Code2Vec, Code2VecConfig
from code2vec_tpu.ops.attention import (
    attention_pool,
    masked_attention_weights,
    streaming_attention_pool,
)


def small_config(**kw):
    defaults = dict(
        terminal_count=50,
        path_count=40,
        label_count=7,
        terminal_embed_size=8,
        path_embed_size=6,
        encode_size=16,
        dropout_prob=0.25,
    )
    defaults.update(kw)
    return Code2VecConfig(**defaults)


def make_batch(rng, B=4, L=10, config=None):
    c = config or small_config()
    starts = rng.integers(1, c.terminal_count, (B, L)).astype(np.int32)
    paths = rng.integers(1, c.path_count, (B, L)).astype(np.int32)
    ends = rng.integers(1, c.terminal_count, (B, L)).astype(np.int32)
    # pad the tail of each row with varying lengths
    for i in range(B):
        n = rng.integers(1, L + 1)
        starts[i, n:] = 0
        paths[i, n:] = 0
        ends[i, n:] = 0
    labels = rng.integers(0, c.label_count, B).astype(np.int32)
    return starts, paths, ends, labels


class TestAttentionPool:
    def test_pad_positions_get_zero_weight(self):
        rng = np.random.default_rng(0)
        ctx = jnp.asarray(rng.normal(size=(2, 5, 3)), jnp.float32)
        mask = jnp.asarray([[1, 1, 0, 0, 0], [1, 1, 1, 1, 1]], jnp.float32)
        a = jnp.asarray(rng.normal(size=3), jnp.float32)
        cv, attn = attention_pool(ctx, mask, a)
        np.testing.assert_allclose(np.asarray(attn[0, 2:]), 0.0, atol=1e-30)
        np.testing.assert_allclose(np.asarray(attn.sum(-1)), 1.0, rtol=1e-6)

    def test_matches_numpy_oracle(self):
        rng = np.random.default_rng(1)
        ctx = rng.normal(size=(3, 6, 4)).astype(np.float32)
        mask = (rng.random((3, 6)) > 0.3).astype(np.float32)
        mask[:, 0] = 1.0  # at least one real position
        a = rng.normal(size=4).astype(np.float32)
        cv, attn = attention_pool(jnp.asarray(ctx), jnp.asarray(mask), jnp.asarray(a))
        scores = ctx @ a
        masked = scores * mask + (1 - mask) * -3.4e38
        e = np.exp(masked - masked.max(-1, keepdims=True))
        expected_attn = e / e.sum(-1, keepdims=True)
        np.testing.assert_allclose(np.asarray(attn), expected_attn, rtol=1e-5)
        expected_cv = np.einsum("bl,ble->be", expected_attn, ctx)
        np.testing.assert_allclose(np.asarray(cv), expected_cv, rtol=1e-5)

    def test_all_masked_row_is_uniform_not_nan(self):
        # mirrors the reference arithmetic: all-NINF row softmaxes to uniform
        attn = masked_attention_weights(
            jnp.zeros((1, 4)), jnp.zeros((1, 4))
        )
        assert not np.isnan(np.asarray(attn)).any()


class TestStreamingAttentionPool:
    """The explicit exp/sum lowering (attn_impl='streaming') is the same
    math as attention_pool — outputs AND gradients must match."""

    def _inputs(self, seed=3, B=4, L=9, E=5):
        rng = np.random.default_rng(seed)
        ctx = jnp.asarray(rng.normal(size=(B, L, E)), jnp.float32)
        mask = jnp.asarray((rng.random((B, L)) > 0.3), jnp.float32)
        mask = mask.at[:, 0].set(1.0)
        a = jnp.asarray(rng.normal(size=E), jnp.float32)
        return ctx, mask, a

    def test_outputs_match_xla_pool(self):
        ctx, mask, a = self._inputs()
        cv_x, attn_x = attention_pool(ctx, mask, a)
        cv_s, attn_s = streaming_attention_pool(ctx, mask, a)
        np.testing.assert_allclose(np.asarray(cv_s), np.asarray(cv_x), rtol=1e-6)
        np.testing.assert_allclose(np.asarray(attn_s), np.asarray(attn_x), rtol=1e-6)

    def test_gradients_match_xla_pool(self):
        ctx, mask, a = self._inputs(seed=4)

        def loss(pool, ctx, a):
            cv, _ = pool(ctx, mask, a)
            return jnp.sum(cv * jnp.cos(cv))

        gx = jax.grad(lambda c, p: loss(attention_pool, c, p), argnums=(0, 1))(ctx, a)
        gs = jax.grad(
            lambda c, p: loss(streaming_attention_pool, c, p), argnums=(0, 1)
        )(ctx, a)
        for a_, b_ in zip(gx, gs):
            np.testing.assert_allclose(np.asarray(b_), np.asarray(a_), rtol=1e-5,
                                       atol=1e-7)

    def test_all_masked_row_not_nan_and_grad_finite(self):
        ctx = jnp.ones((1, 4, 3), jnp.float32)
        mask = jnp.zeros((1, 4), jnp.float32)
        a = jnp.ones(3, jnp.float32)
        cv, attn = streaming_attention_pool(ctx, mask, a)
        assert not np.isnan(np.asarray(attn)).any()
        g = jax.grad(lambda c: jnp.sum(streaming_attention_pool(c, mask, a)[0]))(ctx)
        assert np.isfinite(np.asarray(g)).all()

    def test_unknown_attn_impl_raises(self):
        c = small_config(attn_impl="streamin")
        rng = np.random.default_rng(6)
        starts, paths, ends, _ = make_batch(rng, config=c)
        with pytest.raises(ValueError, match="unknown attn_impl"):
            Code2Vec(c).init(jax.random.PRNGKey(0), starts, paths, ends)

    def test_model_logits_match_across_attn_impl(self):
        c = small_config(dropout_prob=0.0)
        rng = np.random.default_rng(5)
        starts, paths, ends, _ = make_batch(rng, config=c)
        params = Code2Vec(c).init(jax.random.PRNGKey(0), starts, paths, ends)
        logits_x, cv_x, _ = Code2Vec(c).apply(params, starts, paths, ends)
        cs = c.with_updates(attn_impl="streaming")
        logits_s, cv_s, _ = Code2Vec(cs).apply(params, starts, paths, ends)
        np.testing.assert_allclose(
            np.asarray(logits_s), np.asarray(logits_x), rtol=1e-5, atol=1e-6
        )
        np.testing.assert_allclose(
            np.asarray(cv_s), np.asarray(cv_x), rtol=1e-5, atol=1e-6
        )


class TestSplitEncoder:
    """encoder_impl='split' computes the concat matmul as three sliced
    matmuls on the SAME input_dense/kernel param — identical param tree,
    identical init values, identical outputs and gradients."""

    def _configs(self):
        c = small_config(dropout_prob=0.0)
        return c, c.with_updates(encoder_impl="split")

    def test_param_trees_and_init_values_identical(self):
        c, cs = self._configs()
        rng = np.random.default_rng(7)
        starts, paths, ends, _ = make_batch(rng, config=c)
        p1 = Code2Vec(c).init(jax.random.PRNGKey(0), starts, paths, ends)
        p2 = Code2Vec(cs).init(jax.random.PRNGKey(0), starts, paths, ends)
        f1 = jax.tree_util.tree_leaves_with_path(p1)
        f2 = jax.tree_util.tree_leaves_with_path(p2)
        assert [k for k, _ in f1] == [k for k, _ in f2]
        for (k, a), (_, b) in zip(f1, f2):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_outputs_and_grads_match_concat(self):
        c, cs = self._configs()
        rng = np.random.default_rng(8)
        starts, paths, ends, labels = make_batch(rng, config=c)
        params = Code2Vec(c).init(jax.random.PRNGKey(0), starts, paths, ends)
        l1, cv1, _ = Code2Vec(c).apply(params, starts, paths, ends)
        l2, cv2, _ = Code2Vec(cs).apply(params, starts, paths, ends)
        np.testing.assert_allclose(np.asarray(l2), np.asarray(l1),
                                   rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(np.asarray(cv2), np.asarray(cv1),
                                   rtol=1e-5, atol=1e-6)

        def loss(model, p):
            logits, _, _ = model.apply(p, starts, paths, ends)
            return jnp.sum(jax.nn.log_softmax(logits)[:, 0])

        g1 = jax.grad(lambda p: loss(Code2Vec(c), p))(params)
        g2 = jax.grad(lambda p: loss(Code2Vec(cs), p))(params)
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(b), np.asarray(a), rtol=2e-5, atol=1e-6
            ),
            g1, g2,
        )

    def test_unknown_encoder_impl_raises(self):
        c = small_config(encoder_impl="cat")
        rng = np.random.default_rng(9)
        starts, paths, ends, _ = make_batch(rng, config=c)
        with pytest.raises(ValueError, match="unknown encoder_impl"):
            Code2Vec(c).init(jax.random.PRNGKey(0), starts, paths, ends)


class TestCode2VecForward:
    def test_shapes_and_determinism(self):
        c = small_config()
        rng = np.random.default_rng(0)
        starts, paths, ends, labels = make_batch(rng, config=c)
        model = Code2Vec(c)
        params = model.init(jax.random.PRNGKey(0), starts, paths, ends)
        logits, cv, attn = model.apply(params, starts, paths, ends)
        assert logits.shape == (4, c.label_count)
        assert cv.shape == (4, c.encode_size)
        assert attn.shape == (4, 10)
        logits2, _, _ = model.apply(params, starts, paths, ends)
        np.testing.assert_array_equal(np.asarray(logits), np.asarray(logits2))

    def test_pad_contexts_do_not_affect_output(self):
        c = small_config(dropout_prob=0.0)
        rng = np.random.default_rng(2)
        starts, paths, ends, _ = make_batch(rng, B=1, L=8, config=c)
        starts[0, 4:] = 0
        paths[0, 4:] = 0
        ends[0, 4:] = 0
        model = Code2Vec(c)
        params = model.init(jax.random.PRNGKey(0), starts, paths, ends)
        logits_a, cv_a, _ = model.apply(params, starts, paths, ends)
        # change the content of PAD positions — must be invisible
        paths2 = paths.copy()
        paths2[0, 4:] = 7
        ends2 = ends.copy()
        ends2[0, 4:] = 3
        logits_b, cv_b, _ = model.apply(params, starts, paths2, ends2)
        np.testing.assert_allclose(np.asarray(cv_a), np.asarray(cv_b), atol=1e-6)

    def test_dropout_gate(self):
        # dropout_prob outside (0,1) disables dropout entirely
        # (reference: model/model.py:26-29)
        c = small_config(dropout_prob=0.0)
        rng = np.random.default_rng(3)
        starts, paths, ends, _ = make_batch(rng, config=c)
        model = Code2Vec(c)
        params = model.init(jax.random.PRNGKey(0), starts, paths, ends)
        out1, _, _ = model.apply(
            params, starts, paths, ends, deterministic=False,
            rngs={"dropout": jax.random.PRNGKey(1)},
        )
        out2, _, _ = model.apply(params, starts, paths, ends, deterministic=True)
        np.testing.assert_allclose(np.asarray(out1), np.asarray(out2), atol=1e-6)

    def test_dropout_active_in_training(self):
        c = small_config(dropout_prob=0.5)
        rng = np.random.default_rng(4)
        starts, paths, ends, _ = make_batch(rng, config=c)
        model = Code2Vec(c)
        params = model.init(jax.random.PRNGKey(0), starts, paths, ends)
        out1, _, _ = model.apply(
            params, starts, paths, ends, deterministic=False,
            rngs={"dropout": jax.random.PRNGKey(1)},
        )
        out2, _, _ = model.apply(
            params, starts, paths, ends, deterministic=False,
            rngs={"dropout": jax.random.PRNGKey(2)},
        )
        assert np.abs(np.asarray(out1) - np.asarray(out2)).max() > 1e-6

    def test_bfloat16_compute(self):
        c = small_config(dtype=jnp.bfloat16, dropout_prob=0.0)
        rng = np.random.default_rng(5)
        starts, paths, ends, _ = make_batch(rng, config=c)
        model = Code2Vec(c)
        params = model.init(jax.random.PRNGKey(0), starts, paths, ends)
        logits, cv, attn = model.apply(params, starts, paths, ends)
        # heads and outputs stay f32
        assert logits.dtype == jnp.float32
        assert cv.dtype == jnp.float32
        assert not np.isnan(np.asarray(logits)).any()


class TestAngularMarginHead:
    def test_matches_numpy_oracle(self):
        import math

        c = small_config(angular_margin_loss=True, dropout_prob=0.0)
        rng = np.random.default_rng(6)
        starts, paths, ends, labels = make_batch(rng, config=c)
        model = Code2Vec(c)
        params = model.init(
            jax.random.PRNGKey(0), starts, paths, ends, labels=labels
        )
        logits, cv, _ = model.apply(params, starts, paths, ends, labels=labels)

        # oracle from the code vector + margin weight (model/model.py:71-80)
        w = np.asarray(params["params"]["output_margin_weight"])
        cvn = np.asarray(cv)
        cvn = cvn / np.linalg.norm(cvn, axis=-1, keepdims=True)
        wn = w / np.linalg.norm(w, axis=-1, keepdims=True)
        cosine = cvn @ wn.T
        sine = np.sqrt(np.clip(1 - cosine**2, 0, 1))
        phi = cosine * math.cos(0.5) - sine * math.sin(0.5)
        phi = np.where(cosine > 0, phi, cosine)
        one_hot = np.eye(c.label_count)[labels]
        expected = (one_hot * phi + (1 - one_hot) * cosine) * 30.0
        np.testing.assert_allclose(np.asarray(logits), expected, rtol=1e-4, atol=1e-4)

    def test_inference_without_labels_is_plain_cosine(self):
        """labels=None (prediction): the margin is skipped — ArcFace-family
        models rank classes by plain cosine at inference. This is what lets
        `predict` and imported margin-head checkpoints serve label-free."""
        c = small_config(angular_margin_loss=True, dropout_prob=0.0)
        rng = np.random.default_rng(7)
        starts, paths, ends, labels = make_batch(rng, config=c)
        model = Code2Vec(c)
        params = model.init(
            jax.random.PRNGKey(0), starts, paths, ends, labels=labels
        )
        logits, cv, _ = model.apply(params, starts, paths, ends)

        w = np.asarray(params["params"]["output_margin_weight"])
        cvn = np.asarray(cv)
        cvn = cvn / np.linalg.norm(cvn, axis=-1, keepdims=True)
        wn = w / np.linalg.norm(w, axis=-1, keepdims=True)
        np.testing.assert_allclose(
            np.asarray(logits), (cvn @ wn.T) * 30.0, rtol=1e-4, atol=1e-4
        )


class TestEmbedGradModes:
    """ops.embed: all backward formulations produce the same gradients and
    the param tree stays nn.Embed-shaped (checkpoint/sharding compat)."""

    def _grads(self, embed_grad):
        config = small_config(dropout_prob=0.0, embed_grad=embed_grad)
        model = Code2Vec(config)
        rng = np.random.default_rng(3)
        starts, paths, ends, labels = make_batch(rng, config=config)
        params = model.init(jax.random.PRNGKey(0), starts, paths, ends)

        def loss(params):
            logits, _, _ = model.apply(params, starts, paths, ends)
            return (logits.astype(jnp.float32) ** 2).sum()

        return params, jax.grad(loss)(params)

    def test_param_tree_matches_nn_embed_layout(self):
        params, _ = self._grads("dense")
        table = params["params"]["terminal_embedding"]["embedding"]
        assert table.shape == (50, 8) and table.dtype == jnp.float32
        assert params["params"]["path_embedding"]["embedding"].shape == (40, 6)

    @pytest.mark.parametrize("mode", ["segment", "segment_sorted"])
    def test_grads_match_dense(self, mode):
        params_d, grads_d = self._grads("dense")
        params_m, grads_m = self._grads(mode)
        # same init regardless of mode
        jax.tree.map(np.testing.assert_array_equal, params_d, params_m)
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-5),
            grads_d,
            grads_m,
        )

    def test_duplicate_ids_accumulate(self):
        # repeated ids in a batch must sum their contributions in every mode
        table = jnp.eye(4, dtype=jnp.float32)
        from code2vec_tpu.ops.embed import embedding_lookup

        ids = jnp.array([[1, 1, 2]], dtype=jnp.int32)
        for mode in ("dense", "segment", "segment_sorted"):
            g = jax.grad(
                lambda t: embedding_lookup(t, ids, grad_mode=mode).sum()
            )(table)
            np.testing.assert_allclose(g[1], np.full(4, 2.0))
            np.testing.assert_allclose(g[2], np.full(4, 1.0))
            np.testing.assert_allclose(g[0], np.zeros(4))

    def test_invalid_mode_raises(self):
        from code2vec_tpu.ops.embed import embedding_lookup

        with pytest.raises(ValueError):
            embedding_lookup(jnp.zeros((3, 2)), jnp.zeros((1,), jnp.int32),
                             grad_mode="bogus")
