"""Worker process for the real multi-process integration test
(test_multiprocess.py). Not a pytest module.

Forms a 2-process jax.distributed group over the CPU backend (2 local
devices each -> 4 global), loads a host-sharded corpus (this process's
round-robin half), and drives the PRODUCTION host-sharded feed path:
``train()`` with a data axis spanning both processes, batches assembled
via ``make_array_from_process_local_data``. Prints one final JSON line
with the per-epoch losses/f1 so the parent can assert cross-process
agreement.

Usage: mp_worker.py <dataset_dir> <out_dir>
Env:   COORDINATOR_ADDRESS / NUM_PROCESSES / PROCESS_ID (distributed.py)
"""

import json
import os
import sys

# MP_LOCAL_DEVICES=1 lets a 4-process group put the model axis ACROSS
# process boundaries (mesh rows pair devices from different processes),
# exercising cross-process tensor-parallel collectives under Gloo
_LOCAL_DEVICES = int(os.environ.get("MP_LOCAL_DEVICES", "2"))
os.environ["XLA_FLAGS"] = (
    f"--xla_force_host_platform_device_count={_LOCAL_DEVICES}"
)
os.environ["JAX_PLATFORMS"] = "cpu"

import faulthandler

# diagnostics only: dump stacks if a run ever stalls (orbax's multihost
# commit barrier deadlocks if processes are given different checkpoint
# dirs — they must share one, like a pod's shared filesystem)
faulthandler.dump_traceback_later(400, exit=False)

import jax

from code2vec_tpu.parallel.distributed import initialize_from_env


def _shard_staged_main(dataset_dir: str) -> None:
    """MP_SHARD_STAGED=1: the pod-scale composition VERDICT r4 weak-#5
    asked to pin — feed_groups x ShardedStagedCorpus. Each process loads
    ONLY its feed group's corpus shard, stages it host-side, and
    shard_staged_multiprocess assembles the global [D, ...] staged arrays
    from process-local blocks; ShardedEpochRunner then trains chunks over
    the cross-process mesh. The parent asserts lockstep losses AND that
    each host staged only its own shard."""
    import numpy as np

    from code2vec_tpu.data.reader import load_corpus
    from code2vec_tpu.data.synth import SynthSpec, generate_corpus_files
    from code2vec_tpu.models.code2vec import Code2VecConfig
    from code2vec_tpu.parallel.distributed import feed_groups
    from code2vec_tpu.parallel.mesh import make_mesh
    from code2vec_tpu.parallel.shardings import shard_state
    from code2vec_tpu.train.config import TrainConfig
    from code2vec_tpu.train.device_epoch import (
        ShardedEpochRunner,
        shard_staged_multiprocess,
        stage_method_corpus,
    )
    from code2vec_tpu.train.step import create_train_state
    import jax.numpy as jnp

    spec = SynthSpec(
        n_methods=96, n_terminals=120, n_paths=100, n_labels=6,
        mean_contexts=10.0, max_contexts=16, seed=11,
    )
    paths = generate_corpus_files(dataset_dir, spec)

    data_axis = int(os.environ.get("MP_DATA_AXIS", "4"))
    mesh = make_mesh(data=data_axis, model=1, ctx=1)
    group, n_groups = feed_groups(mesh)
    data = load_corpus(
        paths["corpus"], paths["path_idx"], paths["terminal_idx"],
        shard=(group, n_groups),
    )
    # the host-side staging sees ONLY this feed group's shard — the
    # "each host stages only its shard" claim, pinned by construction
    n_local_items = data.n_items
    assert n_local_items < 96, n_local_items

    # group members must stage identically: seed by GROUP, not process
    staged_host = stage_method_corpus(
        data, np.arange(data.n_items), np.random.default_rng(1000 + group),
        device="host",
    )
    local_staged_items = int(staged_host.n_items)
    staged = shard_staged_multiprocess(staged_host, mesh)
    assert staged.n_items == 96, staged.n_items
    local_d = data_axis // n_groups
    my_counts = staged.shard_counts[group * local_d : (group + 1) * local_d]
    assert int(my_counts.sum()) == n_local_items, (my_counts, n_local_items)

    batch, bag, chunk = 16, 16, 2
    mc = Code2VecConfig(
        terminal_count=len(data.terminal_vocab),
        path_count=len(data.path_vocab),
        label_count=len(data.label_vocab), terminal_embed_size=16,
        path_embed_size=16, encode_size=32,
    )
    tc = TrainConfig(batch_size=batch, max_path_length=bag)
    example = {
        "starts": np.zeros((batch, bag), np.int32),
        "paths": np.zeros((batch, bag), np.int32),
        "ends": np.zeros((batch, bag), np.int32),
        "labels": np.zeros(batch, np.int32),
        "example_mask": np.ones(batch, np.float32),
    }
    state = shard_state(mesh, create_train_state(
        tc, mc, jax.random.PRNGKey(0), example
    ))
    cw = jnp.ones(mc.label_count, jnp.float32)
    runner = ShardedEpochRunner(mc, cw, batch, bag, chunk, mesh=mesh)
    run_chunk = runner._train_chunk(chunk)
    span = chunk * runner.per_shard
    valid = np.ones((runner.n_shards, span), np.float32)
    rng = np.random.default_rng(7)  # identical on every process
    key = jax.random.PRNGKey(2)
    losses = []
    for _ in range(3):
        rows = rng.integers(
            0, np.maximum(staged.shard_counts[:, None], 1),
            (runner.n_shards, span),
        ).astype(np.int32)
        key, sub = jax.random.split(key)
        state, loss = run_chunk(
            state, staged.contexts, staged.row_splits, staged.labels,
            rows, valid, sub,
        )
        losses.append(float(loss))
    print(json.dumps({
        "process": jax.process_index(),
        "feed_group": group,
        "n_groups": n_groups,
        "local_items": n_local_items,
        "local_staged_items": local_staged_items,
        "global_items": int(staged.n_items),
        "losses": losses,
        "f1s": [],
        "best_f1": None,
    }), flush=True)


def main() -> None:
    dataset_dir, out_dir = sys.argv[1], sys.argv[2]
    n_procs = int(os.environ["NUM_PROCESSES"])
    assert initialize_from_env(), "worker needs the distributed env vars"
    assert jax.process_count() == n_procs, jax.process_count()
    assert len(jax.devices()) == n_procs * _LOCAL_DEVICES, jax.devices()

    if os.environ.get("MP_SHARD_STAGED", "").strip() == "1":
        return _shard_staged_main(dataset_dir)

    from code2vec_tpu.data.reader import load_corpus
    from code2vec_tpu.data.synth import SynthSpec, generate_corpus_files
    from code2vec_tpu.train.config import TrainConfig
    from code2vec_tpu.train.loop import train

    # out_dir is SHARED between processes (orbax's commit protocol needs
    # one checkpoint dir visible to all, as on a pod); dataset dirs are
    # per-process: both generate identical corpus files (seeded) in their
    # own dir, then each loads only its round-robin half
    spec = SynthSpec(
        n_methods=96, n_terminals=120, n_paths=100, n_labels=6,
        mean_contexts=10.0, max_contexts=16, seed=11,
    )
    paths = generate_corpus_files(dataset_dir, spec)

    cfg = TrainConfig(
        max_epoch=3,
        batch_size=16,
        encode_size=32,
        terminal_embed_size=16,
        path_embed_size=16,
        max_path_length=16,
        # default: the data axis spans every device of every process;
        # MP_MODEL_AXIS=2 (with MP_LOCAL_DEVICES=1) makes each model pair
        # straddle two processes — cross-process TP collectives
        data_axis=int(
            os.environ.get("MP_DATA_AXIS", str(n_procs * _LOCAL_DEVICES))
        ),
        model_axis=int(os.environ.get("MP_MODEL_AXIS", "1")),
        print_sample_cycle=0,
        # elastic-training drills (test_elastic.py): periodic saves so a
        # fault-killed group leaves a restorable checkpoint behind (the
        # fault plan itself arrives via C2V_FAULT_PLAN, which train()
        # reads directly)
        checkpoint_cycle=int(os.environ.get("MP_CHECKPOINT_CYCLE", "0")),
        resume=os.environ.get("MP_RESUME", "").strip() == "1",
        # pin table padding when a drill resumes under a different
        # model_axis (the pad follows model_axis unless pinned, and
        # restore validates it)
        vocab_pad_multiple=int(os.environ.get("MP_VOCAB_PAD", "0")),
    )
    # shard the corpus by FEED GROUP (the processes sharing this one's
    # data-axis coords), not by process index — with a model axis spanning
    # processes the group has 2 members that must load identical shards
    from code2vec_tpu.parallel.distributed import feed_groups
    from code2vec_tpu.train.loop import build_mesh

    shard = feed_groups(build_mesh(cfg))
    data = load_corpus(
        paths["corpus"], paths["path_idx"], paths["terminal_idx"], shard=shard
    )
    assert data.shard == shard

    result = train(cfg, data, out_dir=out_dir)
    # full-precision floats: the parent asserts bit-for-bit agreement
    print(json.dumps({
        "process": jax.process_index(),
        "best_f1": result.best_f1,
        "losses": [h["train_loss"] for h in result.history],
        "f1s": [h["f1"] for h in result.history],
    }), flush=True)


if __name__ == "__main__":
    main()
