"""Golden tests pinning normalization/subtokenization semantics
(reference: model/dataset.py:55-56,86-92 — SURVEY.md §7 hard part (c))."""

from code2vec_tpu.text import (
    normalize_and_subtokenize,
    normalize_method_name,
    subtokenize,
)


class TestNormalizeMethodName:
    def test_strips_underscores_and_digits(self):
        assert normalize_method_name("get_value_2") == "getvalue"

    def test_plain_name_unchanged(self):
        assert normalize_method_name("toString") == "toString"

    def test_leading_underscore(self):
        assert normalize_method_name("_private") == "private"

    def test_digits_inside(self):
        assert normalize_method_name("md5Hash") == "mdHash"

    def test_all_stripped(self):
        assert normalize_method_name("_123_") == ""


class TestSubtokenize:
    # Golden outputs hand-derived from the reference regex
    # ([a-z]+)([A-Z][a-z]+)|([A-Z][a-z]+) used via re.split + filter.
    def test_simple_camel(self):
        assert subtokenize("toString") == ["to", "string"]

    def test_three_tokens(self):
        assert subtokenize("getValueCount") == ["get", "value", "count"]

    def test_single_lower(self):
        # no match at all -> split returns the original string
        assert subtokenize("main") == ["main"]

    def test_leading_capital(self):
        assert subtokenize("Parse") == ["parse"]

    def test_acronym_behavior_pinned(self):
        # Degenerate-but-pinned: "parseHTMLDocument" — the regex cannot split
        # inside acronyms; "HTMLD" has no [A-Z][a-z]+ match until "Document".
        assert subtokenize("parseHTMLDocument") == ["parsehtml", "document"]

    def test_empty(self):
        assert subtokenize("") == []


class TestComposition:
    def test_label_pipeline(self):
        # Exactly what the corpus loader does per label
        # (reference: model/dataset_reader.py:97-100).
        lower, subtokens = normalize_and_subtokenize("writeObject_1")
        assert lower == "writeobject"
        assert subtokens == ("write", "object")

    def test_cache_consistency(self):
        a = normalize_and_subtokenize("equalsIgnoreCase")
        b = normalize_and_subtokenize("equalsIgnoreCase")
        assert a == b == ("equalsignorecase", ("equals", "ignore", "case"))
