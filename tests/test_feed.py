"""Parallel host ingest (ISSUE 14): the plan/build split, the worker pool,
and the bitwise-parity contract.

The load-bearing guarantees:

- every method-task source's ``plan_batches`` draws the SAME rng values its
  ``batches`` would (identical end state) and ``execute_plan`` rebuilds are
  bitwise the sync stream's batches — {fixed-L, bucketed, streaming, mmap}
  x {shuffled, sequential} x {shuffled, corpus order};
- with REAL forked workers and the shared-memory arena, delivered batches,
  order, pad accounting, train histories, and kill->resume cursors are
  bitwise ``--feed_workers 0``;
- arena slots recycle under backpressure without ever overwriting a view
  the consumer still owns (content correctness with slots << batches);
- a worker exception re-raises on the coordinator WITH the child traceback
  text; a killed worker fails the stream instead of hanging it; the pool
  tears down cleanly either way;
- feeding a 65 MB mmap corpus with workers stays O(arena) host RSS
  (RLIMIT_AS-enforced, reusing the PR-10 harness);
- the vectorized variable-task epoch build is bitwise the historical
  per-alias loop (same rng consumption -> same loss multiset).
"""

import json
import os
import signal
import subprocess
import sys
import textwrap
import time

import numpy as np
import pytest

from code2vec_tpu import PAD_INDEX, faultinject
from code2vec_tpu.data.pipeline import (
    BatchPlan,
    EpochSource,
    MmapCorpusSource,
    StreamingSource,
    build_variable_epoch,
    derive_bucket_ladder,
    execute_plan,
    variable_items,
    _index_remap,
    _rename_target,
)
from code2vec_tpu.data.parallel_feed import (
    FeedPool,
    FeedWorkerError,
    ParallelFeed,
)
from code2vec_tpu.data.reader import load_corpus
from code2vec_tpu.data.synth import SPECS, generate_corpus_files
from code2vec_tpu.train.config import TrainConfig
from code2vec_tpu.train.loop import train
from tools.corpus_convert import text_to_csr

pytestmark = pytest.mark.feed

BAG = 32

TINY_CFG = dict(
    max_epoch=2,
    batch_size=32,
    encode_size=64,
    terminal_embed_size=32,
    path_embed_size=32,
    max_path_length=BAG,
    print_sample_cycle=0,
)

METRIC_KEYS = ("train_loss", "test_loss", "accuracy", "precision", "recall", "f1")


@pytest.fixture(scope="module")
def corpora(tmp_path_factory):
    """(text paths, csr path, text-loaded data, mmap-loaded data)."""
    out = tmp_path_factory.mktemp("feed")
    paths = generate_corpus_files(out, SPECS["tiny"])
    csr = str(out / "corpus.csr")
    text_to_csr(paths["corpus"], csr)
    data_text = load_corpus(
        paths["corpus"], paths["path_idx"], paths["terminal_idx"],
        cache=False, native=False,
    )
    data_mmap = load_corpus(csr, paths["path_idx"], paths["terminal_idx"])
    return paths, csr, data_text, data_mmap


@pytest.fixture(autouse=True)
def _clear_fault_plan():
    faultinject.install_plan(None)
    yield
    faultinject.install_plan(None)


def assert_bitwise_history(r1, r2):
    assert len(r1.history) == len(r2.history)
    for h1, h2 in zip(r1.history, r2.history):
        for key in METRIC_KEYS:
            assert h1[key] == h2[key], (h1["epoch"], key, h1[key], h2[key])


def _sources(data_text, data_mmap, ladder, context_order):
    idx = np.arange(data_text.n_items)
    kw = dict(context_order=context_order)
    return {
        "epoch-fixed": (EpochSource(data_text, idx, 8, BAG, **kw), data_text),
        "epoch-bucketed": (
            EpochSource(data_text, idx, 8, BAG, ladder=ladder, **kw),
            data_text,
        ),
        "stream-fixed": (
            StreamingSource(data_text, idx, 8, BAG, 48, **kw), data_text,
        ),
        "stream-bucketed": (
            StreamingSource(data_text, idx, 8, BAG, 48, ladder=ladder, **kw),
            data_text,
        ),
        "mmap-fixed": (
            MmapCorpusSource(data_mmap, idx, 8, BAG, **kw), data_mmap,
        ),
        "mmap-bucketed": (
            MmapCorpusSource(data_mmap, idx, 8, BAG, ladder=ladder, **kw),
            data_mmap,
        ),
    }


# ---------------------------------------------------------------------------
# the plan/build split (no workers: pure functions)
# ---------------------------------------------------------------------------


class TestPlanBuildSplit:
    def test_plan_matrix_bitwise_and_rng_end_state(self, corpora):
        """THE split contract: execute_plan(plan_k) == batches()[k] bitwise
        for every source variant, and a fully-consumed plan stream leaves
        the generator in the identical state (later epochs stay aligned)."""
        _, _, data_text, data_mmap = corpora
        ladder = derive_bucket_ladder(np.diff(data_text.row_splits), BAG)
        assert len(ladder) > 1
        for context_order in ("shuffled", "corpus"):
            sources = _sources(data_text, data_mmap, ladder, context_order)
            for name, (source, data) in sources.items():
                for shuffle in (True, False):
                    tag = f"{name}/{context_order}/shuffle={shuffle}"
                    r1 = np.random.default_rng(7)
                    r2 = np.random.default_rng(7)
                    sync = list(source.batches(r1, shuffle=shuffle))
                    plans = list(source.plan_batches(r2, shuffle=shuffle))
                    assert len(sync) == len(plans), tag
                    for k, (b, p) in enumerate(zip(sync, plans)):
                        got = execute_plan(data, p)
                        for key in b:
                            assert np.array_equal(b[key], got[key]), (
                                tag, k, key,
                            )
                    assert (
                        r1.bit_generator.state == r2.bit_generator.state
                    ), tag

    def test_planned_draws_mismatch_fails_loudly(self, corpora):
        _, _, _, data_mmap = corpora
        fat = int(np.argmax(np.diff(data_mmap.row_splits)))
        plan = BatchPlan(
            width=8, valid=1,
            items=np.asarray([fat], np.int64),
            uniforms=np.zeros(0, np.float64),  # too few for that item's row
        )
        with pytest.raises(ValueError, match="uniforms"):
            execute_plan(data_mmap, plan)

    def test_base_source_has_no_split(self, corpora):
        from code2vec_tpu.data.pipeline import BatchSource

        with pytest.raises(NotImplementedError, match="feed_workers"):
            BatchSource().plan_batches(np.random.default_rng(0))

    def test_variable_task_rejected(self, corpora):
        paths, _, _, _ = corpora
        data = load_corpus(
            paths["corpus"], paths["path_idx"], paths["terminal_idx"],
            cache=False, native=False, infer_method=False,
            infer_variable=True,
        )
        source = EpochSource(data, np.arange(data.n_items), 8, BAG)
        with pytest.raises(ValueError, match="variable"):
            source.plan_batches(np.random.default_rng(0))


# ---------------------------------------------------------------------------
# satellite: the vectorized variable-task epoch build
# ---------------------------------------------------------------------------


def _naive_variable_epoch(
    data, item_idx, max_contexts, rng, shuffle_variable_indexes=False,
    context_order="shuffled",
):
    """The historical per-alias inner loop, kept as the test oracle."""
    from code2vec_tpu import QUESTION_TOKEN_INDEX  # noqa: F401 - parity import

    variable_indexes = data.variable_indexes
    perm_map = None
    if not shuffle_variable_indexes and len(variable_indexes):
        perm_map = _index_remap(variable_indexes, variable_indexes)
    ids, labels, rows_s, rows_p, rows_e = [], [], [], [], []
    label_stoi = data.label_vocab.stoi
    for i, alias_names, alias_idx, s, p, e in variable_items(data, item_idx):
        alias_map = data.aliases[i]
        if shuffle_variable_indexes:
            shuffled = variable_indexes.copy()
            rng.shuffle(shuffled)
            perm_map = _index_remap(variable_indexes, shuffled)
        order = rng.permutation(len(s))
        if context_order == "shuffled":
            s, p, e = s[order], p[order], e[order]
        for alias_name, var_idx in zip(alias_names, alias_idx):
            mine = (s == var_idx) | (e == var_idx)
            ms = _rename_target(s[mine][:max_contexts], var_idx, perm_map)
            mp = p[mine][:max_contexts]
            me = _rename_target(e[mine][:max_contexts], var_idx, perm_map)
            ids.append(int(data.ids[i]))
            labels.append(label_stoi[alias_map[alias_name]])
            rows_s.append(ms)
            rows_p.append(mp)
            rows_e.append(me)
    n = len(ids)
    starts = np.full((n, max_contexts), PAD_INDEX, np.int32)
    paths = np.full((n, max_contexts), PAD_INDEX, np.int32)
    ends = np.full((n, max_contexts), PAD_INDEX, np.int32)
    for r, (ms, mp, me) in enumerate(zip(rows_s, rows_p, rows_e)):
        starts[r, : len(ms)] = ms
        paths[r, : len(mp)] = mp
        ends[r, : len(me)] = me
    return np.asarray(ids, np.int64), starts, paths, ends, np.asarray(
        labels, np.int32
    )


class TestVariableVectorized:
    @pytest.mark.parametrize("svi", [False, True])
    @pytest.mark.parametrize("context_order", ["shuffled", "corpus"])
    def test_bitwise_vs_naive_loop(self, corpora, svi, context_order):
        """rng-consumption compatibility: the vectorized build makes the
        SAME draws in the same order as the per-alias loop, so the epochs
        are bitwise equal — which implies per-example loss-multiset
        parity (the forward is a pure function of the rows)."""
        paths, _, _, _ = corpora
        data = load_corpus(
            paths["corpus"], paths["path_idx"], paths["terminal_idx"],
            cache=False, native=False, infer_method=False,
            infer_variable=True,
        )
        idx = np.arange(data.n_items)
        r1, r2 = np.random.default_rng(3), np.random.default_rng(3)
        got = build_variable_epoch(
            data, idx, 12, r1, shuffle_variable_indexes=svi,
            context_order=context_order,
        )
        ids, starts, paths_a, ends, labels = _naive_variable_epoch(
            data, idx, 12, r2, shuffle_variable_indexes=svi,
            context_order=context_order,
        )
        assert (got.ids == ids).all()
        assert (got.starts == starts).all()
        assert (got.paths == paths_a).all()
        assert (got.ends == ends).all()
        assert (got.labels == labels).all()
        assert r1.bit_generator.state == r2.bit_generator.state


# ---------------------------------------------------------------------------
# the worker pool (real forked processes + shared-memory arena)
# ---------------------------------------------------------------------------


class TestFeedPool:
    def _consume_copy(self, stream):
        out = []
        for batch in stream:
            out.append({k: np.array(v) for k, v in batch.items()})
        return out

    def test_delivered_stream_bitwise_vs_sync(self, corpora):
        _, _, data_text, data_mmap = corpora
        ladder = derive_bucket_ladder(np.diff(data_text.row_splits), BAG)
        idx = np.arange(data_mmap.n_items)
        pool = FeedPool(data_mmap, 2, 8, BAG, deliver="views")
        try:
            source = MmapCorpusSource(data_mmap, idx, 8, BAG, ladder=ladder)
            feed = ParallelFeed(source, pool)
            for shuffle in (True, False):
                r1, r2 = np.random.default_rng(7), np.random.default_rng(7)
                sync = list(source.batches(r1, shuffle=shuffle))
                got = self._consume_copy(feed.batches(r2, shuffle=shuffle))
                assert len(sync) == len(got)
                for k, (a, b) in enumerate(zip(sync, got)):
                    for key in a:
                        assert np.array_equal(a[key], b[key]), (k, key)
                assert r1.bit_generator.state == r2.bit_generator.state
                assert feed.pad_stats() == source.pad_stats()
        finally:
            pool.close()

    def test_arena_recycles_under_backpressure_without_overwrite(
        self, corpora
    ):
        """slots << batches forces every slot through many recycles; a
        slow consumer (device step stand-in) maximizes backpressure. The
        invariant — a view is never overwritten before the consumer moved
        past it — shows up as bitwise-correct content for EVERY batch."""
        _, _, _, data_mmap = corpora
        ladder = derive_bucket_ladder(np.diff(data_mmap.row_splits), BAG)
        idx = np.arange(data_mmap.n_items)
        pool = FeedPool(data_mmap, 2, 8, BAG, slots=3, deliver="views")
        try:
            source = MmapCorpusSource(data_mmap, idx, 8, BAG, ladder=ladder)
            feed = ParallelFeed(source, pool)
            sync = list(source.batches(np.random.default_rng(5)))
            assert len(sync) > pool.slots  # recycling is actually exercised
            stream = feed.batches(np.random.default_rng(5))
            for k, batch in enumerate(stream):
                if k % 7 == 0:
                    time.sleep(0.02)  # let workers run ahead into the arena
                for key in sync[k]:
                    assert np.array_equal(sync[k][key], batch[key]), (k, key)
        finally:
            pool.close()

    def test_worker_exception_carries_child_traceback(self, corpora):
        _, _, _, data_mmap = corpora
        pool = FeedPool(data_mmap, 1, 8, BAG, deliver="views")
        try:
            def bad_plans():
                yield BatchPlan(
                    width=8, valid=1,
                    items=np.asarray([10**9], np.int64),
                    uniforms=np.zeros(0, np.float64),
                )

            with pytest.raises(FeedWorkerError) as err:
                list(pool.run(bad_plans()))
            text = str(err.value)
            assert "feed worker traceback" in text
            assert "Traceback (most recent call last)" in text
            assert err.value.remote_traceback
            # the pool survives a failed stream
            source = MmapCorpusSource(
                data_mmap, np.arange(data_mmap.n_items), 8, BAG
            )
            got = self._consume_copy(
                ParallelFeed(source, pool).batches(np.random.default_rng(1))
            )
            assert got
        finally:
            pool.close()

    def test_worker_kill_fails_fast_and_tears_down(self, corpora):
        from code2vec_tpu.obs.events import EventLog

        _, _, _, data_mmap = corpora
        seen = []
        events = EventLog()
        events.subscribe(lambda e: seen.append(e))
        pool = FeedPool(data_mmap, 2, 8, BAG, deliver="views", events=events)
        source = MmapCorpusSource(
            data_mmap, np.arange(data_mmap.n_items), 8, BAG
        )
        feed = ParallelFeed(source, pool)
        os.kill(pool._procs[0].pid, signal.SIGKILL)
        with pytest.raises(FeedWorkerError, match="died"):
            for _ in feed.batches(np.random.default_rng(2)):
                pass
        assert [e for e in seen if e["event"] == "error"]
        pool.close()
        assert all(not p.is_alive() for p in pool._procs)

    def test_stream_close_midway_then_pool_reusable(self, corpora):
        _, _, _, data_mmap = corpora
        ladder = derive_bucket_ladder(np.diff(data_mmap.row_splits), BAG)
        idx = np.arange(data_mmap.n_items)
        pool = FeedPool(data_mmap, 2, 8, BAG, deliver="views")
        try:
            source = MmapCorpusSource(data_mmap, idx, 8, BAG, ladder=ladder)
            feed = ParallelFeed(source, pool)
            stream = feed.batches(np.random.default_rng(3))
            next(stream)
            stream.close()
            sync = list(source.batches(np.random.default_rng(4)))
            got = self._consume_copy(feed.batches(np.random.default_rng(4)))
            assert len(sync) == len(got)
            for a, b in zip(sync, got):
                assert np.array_equal(a["paths"], b["paths"])
        finally:
            pool.close()

    def test_scheduled_batches_rejected(self, corpora):
        _, _, _, data_mmap = corpora
        pool = FeedPool(data_mmap, 1, 8, BAG, deliver="views")
        try:
            feed = ParallelFeed(
                MmapCorpusSource(
                    data_mmap, np.arange(data_mmap.n_items), 8, BAG
                ),
                pool,
            )
            with pytest.raises(NotImplementedError, match="sharded"):
                feed.scheduled_batches(
                    np.random.default_rng(0), np.asarray([BAG])
                )
        finally:
            pool.close()


# ---------------------------------------------------------------------------
# prefetch-boundary satellite: traceback text across the thread boundary
# ---------------------------------------------------------------------------


class TestPrefetchTraceback:
    def test_producer_exception_carries_traceback_text(self):
        from code2vec_tpu.train.prefetch import HostPrefetcher

        def exploding():
            yield {"x": np.zeros(1)}
            raise ValueError("kaboom-in-producer")

        pf = HostPrefetcher(exploding(), lambda b: b, depth=2)
        with pytest.raises(ValueError, match="kaboom") as err:
            for _ in pf:
                pass
        assert "kaboom-in-producer" in err.value.remote_traceback
        assert "Traceback (most recent call last)" in err.value.remote_traceback


# ---------------------------------------------------------------------------
# end-to-end through train(): bitwise vs --feed_workers 0
# ---------------------------------------------------------------------------


class TestTrainE2E:
    def test_mmap_bucketed_bitwise_and_zero_recompiles(self, corpora):
        """The flagship combination: mmap-CSR + bucketed + prefetched +
        2 feed workers — bitwise the workers=0 history, ladder-only
        compiles."""
        from code2vec_tpu.obs.events import EventLog

        _, _, _, data_mmap = corpora
        base = dict(TINY_CFG, bucketed=True, prefetch_batches=2)
        r0 = train(
            TrainConfig(**base, feed_workers=0), data_mmap, sinks=()
        )
        seen = []
        events = EventLog()
        events.subscribe(lambda e: seen.append(e))
        r2 = train(
            TrainConfig(**base, feed_workers=2), data_mmap, sinks=(),
            events=events,
        )
        assert_bitwise_history(r0, r2)
        assert not [e for e in seen if e["event"] == "recompile"]
        assert all(
            0.0 < h["pad_efficiency"] <= 1.0 for h in r2.history
        )

    def test_streaming_sync_bitwise(self, corpora):
        _, _, data_text, _ = corpora
        base = dict(TINY_CFG, bucketed=True, stream_chunk_items=64)
        r0 = train(TrainConfig(**base, feed_workers=0), data_text, sinks=())
        r2 = train(TrainConfig(**base, feed_workers=2), data_text, sinks=())
        assert_bitwise_history(r0, r2)

    def test_kill_resume_bitwise_with_workers(self, corpora, tmp_path):
        """Mid-epoch kill -> --resume with workers ON: the replay skips
        planned batches through the pool and continues bitwise (the
        stream stays a pure function of the epoch-start rng)."""
        _, _, _, data_mmap = corpora
        base = dict(
            TINY_CFG, max_epoch=3, checkpoint_cycle=1,
            bucketed=True, bucket_ladder=f"8,16,{BAG}", feed_workers=2,
        )
        r_full = train(
            TrainConfig(**base), data_mmap, out_dir=str(tmp_path / "full"),
            sinks=(),
        )
        with pytest.raises(faultinject.FaultInjected):
            train(
                TrainConfig(**base, checkpoint_every_steps=2,
                            fault_plan="train_step@9:raise"),
                data_mmap, out_dir=str(tmp_path / "killed"), sinks=(),
            )
        r_resumed = train(
            TrainConfig(**base, resume=True), data_mmap,
            out_dir=str(tmp_path / "killed"), sinks=(),
        )
        assert_bitwise_history(r_full, r_resumed)

    def test_profiler_reports_feed_wait(self, corpora):
        _, _, _, data_mmap = corpora
        res = train(
            TrainConfig(**dict(TINY_CFG, max_epoch=1), feed_workers=2,
                        profile_steps=2),
            data_mmap, sinks=(),
        )
        assert "feed_wait_ms" in res.history[0]
        assert res.history[0]["feed_wait_ms"] >= 0.0

    def test_loud_rejects(self, corpora):
        paths, _, _, data_mmap = corpora
        with pytest.raises(ValueError, match="feed_workers must be >= 0"):
            train(TrainConfig(**TINY_CFG, feed_workers=-1), data_mmap)
        with pytest.raises(ValueError, match="device_epoch"):
            train(
                TrainConfig(**TINY_CFG, feed_workers=2, device_epoch=True),
                data_mmap,
            )
        data_var = load_corpus(
            paths["corpus"], paths["path_idx"], paths["terminal_idx"],
            cache=False, native=False, infer_method=False,
            infer_variable=True,
        )
        with pytest.raises(ValueError, match="method task"):
            train(
                TrainConfig(
                    **TINY_CFG, feed_workers=2, infer_method_name=False,
                    infer_variable_name=True,
                ),
                data_var,
            )

    def test_cli_wiring(self):
        from code2vec_tpu.cli import build_parser, config_from_args

        args = build_parser().parse_args(["--feed_workers", "3"])
        assert config_from_args(args).feed_workers == 3
        assert config_from_args(
            build_parser().parse_args([])
        ).feed_workers == 0


# ---------------------------------------------------------------------------
# obs: fingerprint + worker trace tracks
# ---------------------------------------------------------------------------


class TestObsSatellites:
    def test_host_cpu_fingerprint_stable_and_keyed_into_cache_dir(self):
        from code2vec_tpu.obs.runtime import host_cpu_fingerprint

        fp = host_cpu_fingerprint()
        assert fp == host_cpu_fingerprint()
        assert len(fp) == 8
        int(fp, 16)  # hex digest
        # conftest keyed the suite's compile-cache dir by it (unless an
        # operator pinned the env var before pytest started)
        cache_dir = os.environ["JAX_COMPILATION_CACHE_DIR"]
        if cache_dir.startswith("/tmp/jaxcache_tests_"):
            assert cache_dir.endswith(fp)

    def test_span_complete_lands_on_named_track(self):
        from code2vec_tpu.obs.trace import Tracer

        tracer = Tracer(process_index=0)
        t0 = time.perf_counter()
        tracer.span_complete(
            "feed_build", category="data", start_s=t0,
            end_s=t0 + 0.001, track="feed-worker-1", seq=0,
        )
        trace = tracer.chrome_trace()
        names = [
            e["args"]["name"]
            for e in trace["traceEvents"]
            if e.get("name") == "thread_name"
        ]
        assert "feed-worker-1" in names
        spans = [
            e for e in trace["traceEvents"] if e.get("name") == "feed_build"
        ]
        assert spans and spans[0]["dur"] > 0

    def test_feed_gauges_registered(self, corpora):
        """queue-depth gauge + starved-steps counter ride the run's
        RuntimeHealth and surface in epoch events."""
        from code2vec_tpu.obs.events import EventLog

        _, _, _, data_mmap = corpora
        seen = []
        events = EventLog()
        events.subscribe(lambda e: seen.append(e))
        train(
            TrainConfig(**dict(TINY_CFG, max_epoch=1), feed_workers=2),
            data_mmap, sinks=(), events=events,
        )
        epochs = [e for e in seen if e["event"] == "epoch"]
        assert epochs
        gauges = epochs[0]["health"]["gauges"]
        assert "feed.queue_depth" in gauges


# ---------------------------------------------------------------------------
# bounded host RSS with workers on (the PR-10 RLIMIT_AS harness)
# ---------------------------------------------------------------------------


WORKER_RSS_SCRIPT = textwrap.dedent("""
    import os, resource, sys
    import numpy as np

    from code2vec_tpu.data.reader import load_corpus_csr
    from code2vec_tpu.data.pipeline import MmapCorpusSource, derive_bucket_ladder_hist
    from code2vec_tpu.data.parallel_feed import FeedPool, ParallelFeed

    csr_path, path_idx, terminal_idx = sys.argv[1:4]

    def vm_size():
        with open("/proc/self/status") as f:
            for line in f:
                if line.startswith("VmSize:"):
                    return int(line.split()[1]) * 1024
        raise RuntimeError("no VmSize")

    corpus_bytes = os.path.getsize(csr_path)
    # budget BEFORE the load and the pool: the single corpus-sized term
    # covers the (shared) mmap, the margin covers the arena + queues, and
    # forked workers inherit the limit — so every process, coordinator
    # AND builders, is bound; worker builds must never materialize
    # corpus-sized memory anywhere
    margin = 56 << 20
    budget = vm_size() + corpus_bytes + margin
    resource.setrlimit(resource.RLIMIT_AS, (budget, budget))
    data = load_corpus_csr(csr_path, path_idx, terminal_idx)
    assert data.mmap_backed
    lengths, weights = np.unique(np.diff(data.row_splits), return_counts=True)
    ladder = derive_bucket_ladder_hist(lengths, weights, 200)
    source = MmapCorpusSource(
        data, np.arange(data.n_items), 64, 200, ladder=ladder
    )
    pool = FeedPool(data, 2, 64, int(ladder[-1]), deliver="views")
    feed = ParallelFeed(source, pool)

    n = 0
    stream = feed.batches(np.random.default_rng(0))
    for batch in stream:
        n += 1
        if n >= 40:
            break
    stream.close()
    assert n == 40, n
    pool.close()

    # negative control: materializing the context arrays (an in-RAM load)
    # must blow the same budget
    try:
        hoard = [np.array(data.starts), np.array(data.paths), np.array(data.ends)]
        print("CONTROL-SURVIVED", len(hoard))
        sys.exit(3)
    except MemoryError:
        pass
    print("BOUNDED-OK", n)
""")


@pytest.mark.skipif(sys.platform != "linux", reason="rlimit/VmSize probe")
def test_worker_feed_bounded_by_rlimit(tmp_path, corpora):
    """Workers on the 65 MB mmap corpus stay O(arena): the PR-10
    address-space budget holds with the pool + arena live (jax-free
    subprocess; views delivery needs no backend)."""
    from code2vec_tpu.formats.corpus_io import CorpusRecord, write_corpus_csr

    paths, _, _, _ = corpora
    rng = np.random.default_rng(0)
    big = str(tmp_path / "big.csr")
    n_methods, ctx_per = 6000, 900  # ~65 MB of context sections
    records = (
        CorpusRecord(
            id=i,
            label=f"m{i}",
            path_contexts=rng.integers(
                1, 1000, size=(ctx_per, 3), dtype=np.int64
            ).tolist(),
            aliases=[],
        )
        for i in range(n_methods)
    )
    write_corpus_csr(big, records, terminal_shift=1)
    assert os.path.getsize(big) > 60 << 20

    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    proc = subprocess.run(
        [sys.executable, "-c", WORKER_RSS_SCRIPT, big,
         paths["path_idx"], paths["terminal_idx"]],
        capture_output=True, text=True, timeout=300,
        cwd=repo_root,
        env={
            "PATH": os.environ.get("PATH", ""),
            "HOME": os.environ.get("HOME", "/tmp"),
            "PYTHONPATH": repo_root,
            "OMP_NUM_THREADS": "1",
            "OPENBLAS_NUM_THREADS": "1",
        },
    )
    assert proc.returncode == 0, (proc.stdout, proc.stderr)
    assert "BOUNDED-OK" in proc.stdout


# ---------------------------------------------------------------------------
# CLI smoke: the feed-smoke core (real CLI, csr corpus, workers on)
# ---------------------------------------------------------------------------


def test_cli_trains_with_feed_workers(corpora, tmp_path):
    paths, csr, _, _ = corpora
    from code2vec_tpu.cli import main

    events_dir = tmp_path / "events"
    main([
        "--corpus_path", csr,
        "--path_idx_path", paths["path_idx"],
        "--terminal_idx_path", paths["terminal_idx"],
        "--corpus_format", "csr",
        "--bucketed",
        "--prefetch_batches", "2",
        "--feed_workers", "2",
        "--batch_size", "32",
        "--max_path_length", str(BAG),
        "--encode_size", "64",
        "--terminal_embed_size", "32",
        "--path_embed_size", "32",
        "--max_epoch", "1",
        "--print_sample_cycle", "0",
        "--model_path", str(tmp_path / "out"),
        "--events_dir", str(events_dir),
    ])
    log_files = list(events_dir.glob("*.jsonl"))
    assert log_files
    events = [
        json.loads(line) for line in log_files[0].read_text().splitlines()
    ]
    assert any(e.get("event") == "epoch" for e in events)
    assert not [e for e in events if e.get("event") == "recompile"]
