"""Fleet-wide observability plane (ISSUE 15).

The load-bearing contracts pinned here:

- a :class:`TraceContext` stamped at router admission rides the request
  dict across the stdio pipe UNCHANGED, and every hop tags its spans
  with the id — so a real 2-replica subprocess fleet under a pipelined
  burst yields per-process trace files that ``tools/trace_stitch.py``
  merges into one valid Chrome trace in which at least one request has
  router -> replica -> batcher -> engine spans sharing ONE trace id
  (with the coalesce-aware ``trace_ids`` link on batched device spans);
- ``GET /metrics`` serves Prometheus text exposition 0.0.4 that parses
  and AGREES with the ``health`` op's counters, on the worker (its own
  registry) and on the router (aggregated with a ``replica`` label from
  the prober's lock-light ``last_health`` snapshots);
- router-level sheds (``overloaded``/``deadline``) count into the
  per-op ``serve.op.<op>.errors`` counters — 429s are visible per op,
  not only per class (the PR's shed-visibility satellite);
- ``RuntimeHealth.snapshot()`` carries ``started_unix`` + a monotonic
  ``snapshot_seq`` so two scrapes can compute honest rates and detect
  counter resets across replica respawns;
- SLO burn accounting: rolling error-budget windows per class, burn-rate
  gauges, an edge-triggered ``slo_budget_exhausted`` event, recovery;
- the slow-request flight recorder captures full span breakdowns at a
  threshold or sampled at p99, bounded, dumped as ``flight_*.json``.
"""

from __future__ import annotations

import glob
import json
import os
import subprocess
import sys
import threading
import time
from concurrent.futures import Future

import pytest

from code2vec_tpu.obs.events import EventLog
from code2vec_tpu.obs.runtime import (
    FlightRecorder,
    LatencyHistogram,
    RuntimeHealth,
    parse_prometheus_text,
    prometheus_metric_name,
    prometheus_text,
)
from code2vec_tpu.obs.trace import (
    TraceContext,
    Tracer,
    current_trace_scope,
    ensure_trace,
    get_tracer,
    set_tracer,
    trace_scope,
)
from code2vec_tpu.serve.fleet.replica import ReplicaDied
from code2vec_tpu.serve.fleet.router import FleetRouter
from code2vec_tpu.serve.fleet.slo import (
    DEFAULT_SLO,
    SloBurnTracker,
    SloClass,
)

pytestmark = pytest.mark.obsfleet

TOOLS = os.path.join(os.path.dirname(__file__), "..", "tools")
sys.path.insert(0, TOOLS)
import trace_stitch  # noqa: E402  (tools/ is script-style, not a package)


# ---------------------------------------------------------------------------
# trace context
# ---------------------------------------------------------------------------


def test_trace_context_stamp_and_honor():
    request = {"op": "embed", "source": "x"}
    ctx = ensure_trace(request)
    assert request["trace"]["trace_id"] == ctx.trace_id
    # a second admission (or a downstream process) HONORS the stamp
    again = ensure_trace(request)
    assert again.trace_id == ctx.trace_id
    parsed = TraceContext.from_request(request)
    assert parsed is not None and parsed.trace_id == ctx.trace_id
    # client-supplied contexts pass through verbatim
    client = {"op": "embed", "trace": {"trace_id": "abc123",
                                       "parent_span_id": "dead"}}
    honored = ensure_trace(client)
    assert honored.trace_id == "abc123"
    assert honored.parent_span_id == "dead"


def test_trace_context_ignores_garbage():
    for garbage in (
        {"trace": "not-a-dict"},
        {"trace": {"trace_id": 7}},
        {"trace": {"trace_id": ""}},
        {"trace": {}},
        {},
    ):
        assert TraceContext.from_request(dict(garbage)) is None
    # ensure_trace replaces garbage with a fresh stamp instead of dying
    request = {"op": "embed", "trace": "zzz"}
    ctx = ensure_trace(request)
    assert request["trace"]["trace_id"] == ctx.trace_id


def test_trace_scope_nests_and_restores():
    assert current_trace_scope() == {}
    with trace_scope(trace_ids=["a"]):
        assert current_trace_scope() == {"trace_ids": ["a"]}
        with trace_scope(extra=1):
            assert current_trace_scope() == {"trace_ids": ["a"], "extra": 1}
        assert current_trace_scope() == {"trace_ids": ["a"]}
    assert current_trace_scope() == {}


def test_trace_scope_is_thread_local():
    seen = {}

    def other():
        seen["scope"] = current_trace_scope()

    with trace_scope(trace_ids=["a"]):
        t = threading.Thread(target=other)
        t.start()
        t.join()
    assert seen["scope"] == {}


# ---------------------------------------------------------------------------
# prometheus exposition
# ---------------------------------------------------------------------------


def test_prometheus_metric_name_sanitization():
    assert prometheus_metric_name("serve.op.embed.e2e_ms") == (
        "c2v_serve_op_embed_e2e_ms"
    )
    assert prometheus_metric_name("fleet.r0.in_flight") == (
        "c2v_fleet_r0_in_flight"
    )
    assert prometheus_metric_name("weird-name with spaces") == (
        "c2v_weird_name_with_spaces"
    )


def test_prometheus_text_round_trip_and_agreement():
    health = RuntimeHealth()
    health.counter("serve_requests").inc(42)
    health.counter("serve.op.embed.errors").inc(3)
    health.gauge("serve_queue_depth").set(5)
    health.gauge("serve_transport").set("stdio")  # non-numeric: skipped
    for v in (1.0, 2.0, 3.0, 100.0):
        health.latency("serve.e2e_ms").record(v)
    snap = health.snapshot()
    text = prometheus_text([({}, snap)])
    assert text.startswith("# TYPE")
    parsed = parse_prometheus_text(text)
    types = parsed["# types"]
    # agreement with the health snapshot, series for series
    assert parsed["c2v_serve_requests_total"][0]["value"] == 42
    assert types["c2v_serve_requests_total"] == "counter"
    assert parsed["c2v_serve_op_embed_errors_total"][0]["value"] == 3
    assert parsed["c2v_serve_queue_depth"][0]["value"] == 5
    assert "c2v_serve_transport" not in parsed
    assert types["c2v_serve_e2e_ms"] == "summary"
    quantiles = {
        row["labels"]["quantile"]: row["value"]
        for row in parsed["c2v_serve_e2e_ms"]
    }
    assert quantiles["0.5"] == snap["latencies_ms"]["serve.e2e_ms"]["p50_ms"]
    assert parsed["c2v_serve_e2e_ms_sum"][0]["value"] == 106.0
    assert parsed["c2v_serve_e2e_ms_count"][0]["value"] == 4
    assert parsed["c2v_process_start_time_seconds"][0]["value"] == pytest.approx(
        snap["started_unix"]
    )


def test_prometheus_labels_and_merged_type_headers():
    snap_a = {"counters": {"x": 1}}
    snap_b = {"counters": {"x": 2}}
    text = prometheus_text([
        ({}, snap_a), ({"replica": "r0"}, snap_b),
    ])
    # ONE TYPE header for the metric, both series under it
    assert text.count("# TYPE c2v_x_total counter") == 1
    parsed = parse_prometheus_text(text)
    by_labels = {
        tuple(sorted(row["labels"].items())): row["value"]
        for row in parsed["c2v_x_total"]
    }
    assert by_labels[()] == 1
    assert by_labels[(("replica", "r0"),)] == 2


def test_parse_prometheus_rejects_garbage():
    with pytest.raises(ValueError, match="bad exposition line"):
        parse_prometheus_text("this is { not exposition")


def test_prometheus_label_escaping_round_trips():
    hostile = 'node"1\\with\nnewline'
    text = prometheus_text([({"replica": hostile}, {"counters": {"x": 1}})])
    # the newline is escaped, not emitted: TYPE header + ONE sample line
    assert len(text.splitlines()) == 2
    parsed = parse_prometheus_text(text)
    assert parsed["c2v_x_total"][0]["labels"]["replica"] == hostile


def test_snapshot_start_time_and_sequence_detect_resets():
    health = RuntimeHealth()
    first = health.snapshot()
    second = health.snapshot()
    assert second["started_unix"] == first["started_unix"]
    assert second["snapshot_seq"] == first["snapshot_seq"] + 1
    # a "respawned" process = fresh registry: the reset is detectable
    respawned = RuntimeHealth().snapshot()
    assert respawned["snapshot_seq"] < second["snapshot_seq"] or (
        respawned["started_unix"] >= first["started_unix"]
    )


def test_latency_histogram_tracks_all_time_sum():
    hist = LatencyHistogram(max_samples=2)
    for v in (1.0, 2.0, 3.0, 4.0):
        hist.record(v)  # window holds 2, sum holds all 4
    summary = hist.summary()
    assert summary["sum_ms"] == 10.0
    assert summary["count"] == 4


# ---------------------------------------------------------------------------
# flight recorder
# ---------------------------------------------------------------------------


def test_flight_recorder_threshold_capture_and_bounds(tmp_path):
    events = EventLog()
    seen = []
    events.subscribe(lambda e: seen.append(e))
    health = RuntimeHealth()
    flight = FlightRecorder(
        capacity=3, threshold_ms=10.0, events=events, health=health
    )
    assert not flight.observe(5.0, {"trace_id": "fast"})
    for i in range(5):
        assert flight.observe(10.0 + i, {"trace_id": f"slow{i}"})
    assert flight.count == 5
    assert health.snapshot()["counters"]["flight.recorded"] == 5
    records = flight.snapshot()
    assert len(records) == 3  # bounded: oldest evicted
    assert [r["trace_id"] for r in records] == ["slow2", "slow3", "slow4"]
    assert all(r["e2e_ms"] >= 10.0 for r in records)
    # every capture is also a `flight` event
    flights = [e for e in seen if e["event"] == "flight"]
    assert len(flights) == 5 and flights[0]["trace_id"] == "slow0"
    # and dumps as flight_<seq>.json files
    paths = flight.dump(str(tmp_path / "flight"))
    assert len(paths) == 3
    assert all(os.path.basename(p).startswith("flight_") for p in paths)
    reloaded = json.loads(open(paths[0]).read())
    assert reloaded["trace_id"] == "slow2"


def test_flight_recorder_p99_sampling_captures_the_tail():
    flight = FlightRecorder(capacity=256)
    captured = 0
    # 900 jittered-fast requests with a 60x outlier every 100th: past the
    # warmup floor the outliers always clear the rolling p99 estimate,
    # while the bulk of the stream stays uncaptured (~1% sampling)
    for i in range(900):
        jitter = ((i * 2654435761) % 4093) / 4093.0
        e2e = 100.0 if i % 100 == 99 else 1.0 + jitter * 0.5
        captured += bool(flight.observe(e2e, {"e2e_in": e2e}))
    assert flight.seen == 900
    outliers = [r for r in flight.snapshot() if r["e2e_ms"] == 100.0]
    assert len(outliers) >= 5  # the tail past warmup
    assert captured <= 90  # and NOT the bulk of the stream


# ---------------------------------------------------------------------------
# SLO burn accounting
# ---------------------------------------------------------------------------


def test_burn_tracker_math_gauges_and_exhaustion_event():
    events = EventLog()
    seen = []
    events.subscribe(lambda e: seen.append(e))
    health = RuntimeHealth()
    clock = [1000.0]
    tracker = SloBurnTracker(
        ["embed"], objective=0.9, window_s=10.0, min_requests=5,
        health=health, events=events, clock=lambda: clock[0],
    )
    for _ in range(9):
        tracker.record("embed", good=True)
    tracker.record("embed", good=False)
    snap = tracker.snapshot()["embed"]
    # 1 bad of 10 at a 10% budget: burning at exactly 1.0
    assert snap["burn_rate"] == pytest.approx(1.0)
    assert snap["exhausted"] is True
    gauges = health.snapshot()["gauges"]
    assert gauges["slo.embed.burn_rate"] == pytest.approx(1.0)
    assert gauges["slo.embed.budget_exhausted"] == 1
    exhausted = [e for e in seen if e["event"] == "slo_budget_exhausted"]
    assert len(exhausted) == 1  # edge-triggered, once per episode
    assert exhausted[0]["slo_class"] == "embed"
    # more bad traffic does NOT re-fire while still exhausted
    tracker.record("embed", good=False)
    assert len(
        [e for e in seen if e["event"] == "slo_budget_exhausted"]
    ) == 1
    # recovery: the window rolls past the bad requests
    clock[0] += 100.0
    for _ in range(20):
        tracker.record("embed", good=True)
    snap = tracker.snapshot()["embed"]
    assert snap["exhausted"] is False and snap["burn_rate"] == 0.0
    assert health.snapshot()["gauges"]["slo.embed.budget_exhausted"] == 0
    # ... and a NEW episode fires a NEW event
    for _ in range(20):
        tracker.record("embed", good=False)
    assert len(
        [e for e in seen if e["event"] == "slo_budget_exhausted"]
    ) == 2


def test_burn_tracker_min_requests_floor():
    tracker = SloBurnTracker(
        ["embed"], objective=0.999, window_s=10.0, min_requests=10,
        clock=lambda: 0.0,
    )
    tracker.record("embed", good=False)  # 100% error rate, 1 request
    assert tracker.snapshot()["embed"]["exhausted"] is False


def test_burn_tracker_rejects_bad_config():
    with pytest.raises(ValueError, match="objective"):
        SloBurnTracker(["embed"], objective=1.5)
    with pytest.raises(ValueError, match="window_s"):
        SloBurnTracker(["embed"], window_s=0.1)
    with pytest.raises(ValueError, match="at least one"):
        SloBurnTracker([])


# ---------------------------------------------------------------------------
# router: trace stamping, per-op shed counters, /metrics aggregation
# (in-process fake replicas — no jax, no subprocesses)
# ---------------------------------------------------------------------------


class MiniReplica:
    """Round-trips request dicts through JSON (like the real pipe) and
    answers ok after ``latency_s`` on a worker thread."""

    def __init__(self, slot, incarnation=0, latency_s=0.0):
        self.slot = slot
        self.incarnation = incarnation
        self.latency_s = latency_s
        self.sent: list[dict] = []
        self.probe_failures = 0
        self.last_health: dict | None = None
        self.death_reason = None
        self.pid = 50000 + slot
        self._alive = True
        self._inflight = 0
        self._lock = threading.Lock()

    @property
    def alive(self):
        return self._alive

    @property
    def in_flight(self):
        return self._inflight

    def send(self, request):
        if not self._alive:
            raise ReplicaDied(f"mini r{self.slot} dead")
        self.sent.append(json.loads(json.dumps(request)))  # wire copy
        future: Future = Future()
        with self._lock:
            self._inflight += 1

        def run():
            if self.latency_s:
                time.sleep(self.latency_s)
            with self._lock:
                self._inflight -= 1
            future.set_result(
                {"ok": True, "op": request.get("op"), "slot": self.slot}
            )

        threading.Thread(target=run, daemon=True).start()
        return future

    def wait_ready(self, timeout):
        return {"ok": True}

    def stop(self, timeout=10.0):
        self._alive = False

    def kill(self, timeout=10.0):
        self._alive = False


def make_router(replicas, **kw):
    kw.setdefault("health", RuntimeHealth())
    kw.setdefault("probe_interval_s", 60.0)
    return FleetRouter(
        lambda slot, incarnation: replicas[slot], len(replicas), **kw
    )


def test_router_stamps_trace_at_admission_and_honors_client():
    fakes = [MiniReplica(0)]
    router = make_router(fakes)
    try:
        assert router.handle({"op": "embed", "source": "x"})["ok"]
        stamped = fakes[0].sent[-1]
        assert stamped["trace"]["trace_id"]  # router minted one
        assert router.handle({
            "op": "embed", "source": "x",
            "trace": {"trace_id": "client-chose-this"},
        })["ok"]
        assert fakes[0].sent[-1]["trace"]["trace_id"] == "client-chose-this"
    finally:
        router.close()


def test_router_budget_shed_counts_per_op_errors_and_burns():
    """The shed-visibility satellite: router-level sheds never reach the
    worker's resolver, so serve.op.<op>.errors must be counted AT the
    router or 429s stay invisible per op."""
    slo = {
        "health": DEFAULT_SLO["health"],
        "embed": SloClass("embed", budget=2, deadline_ms=10_000.0),
        "neighbors": DEFAULT_SLO["neighbors"],
    }
    health = RuntimeHealth()
    router = make_router(
        [MiniReplica(0, latency_s=0.2)], slo=slo, health=health,
        per_replica_inflight=1,
    )
    try:
        resolvers = [
            router.handle_async({"op": "embed", "source": "x"})
            for _ in range(8)
        ]
        payloads = [r() for r in resolvers]
        shed = [p for p in payloads if p.get("error_kind") == "overloaded"]
        served = [p for p in payloads if p.get("ok")]
        assert shed and served
        counters = health.snapshot()["counters"]
        # every admitted-or-shed request counted per op; every shed an
        # error per op (NOT only under slo.embed.*)
        assert counters["serve.op.embed.requests"] == 8
        assert counters["serve.op.embed.errors"] >= len(shed)
        assert counters["slo.embed.shed_budget"] == len(shed)
        # and the shed traffic burned error budget
        gauges = health.snapshot()["gauges"]
        assert gauges["slo.embed.burn_rate"] > 0
    finally:
        router.close()


def test_router_deadline_shed_counts_per_op_errors():
    slo = {
        "health": DEFAULT_SLO["health"],
        "embed": SloClass("embed", budget=64, deadline_ms=80.0),
        "neighbors": DEFAULT_SLO["neighbors"],
    }
    health = RuntimeHealth()
    router = make_router(
        [MiniReplica(0, latency_s=0.3)], slo=slo, health=health,
        per_replica_inflight=1,
    )
    try:
        payloads = [
            r() for r in [
                router.handle_async({"op": "embed", "source": "x"})
                for _ in range(4)
            ]
        ]
        kinds = [p.get("error_kind") for p in payloads]
        assert "deadline" in kinds
        counters = health.snapshot()["counters"]
        assert counters["serve.op.embed.errors"] >= kinds.count("deadline")
        assert counters["slo.embed.shed_deadline"] >= 1
    finally:
        router.close()


def test_router_does_not_double_count_worker_relayed_errors():
    """A worker-relayed error payload (e.g. the replica's own batcher
    overloaded) was already counted in THAT replica's registry; the
    router must not count it again into its per-op error series, or the
    aggregated /metrics shows it twice. It still burns error budget."""

    class OverloadedReplica(MiniReplica):
        def send(self, request):
            if request.get("op") == "embed":
                self.sent.append(dict(request))
                future: Future = Future()
                future.set_result({
                    "error": "serving queue is full",
                    "error_kind": "overloaded",
                })
                return future
            return super().send(request)

    health = RuntimeHealth()
    router = make_router([OverloadedReplica(0)], health=health)
    try:
        payload = router.handle({"op": "embed", "source": "x"})
        assert payload["error_kind"] == "overloaded"
        counters = health.snapshot()["counters"]
        assert counters["serve.op.embed.requests"] == 1
        # worker-origin error: NOT in the router's per-op error counter
        assert counters.get("serve.op.embed.errors", 0) == 0
        # but it DID burn budget (the fleet failed the client)
        assert health.snapshot()["gauges"]["slo.embed.burn_rate"] > 0
    finally:
        router.close()


def test_router_flight_recorder_captures_breakdowns():
    health = RuntimeHealth()
    flight = FlightRecorder(threshold_ms=0.001, health=health)
    router = make_router([MiniReplica(0)], health=health, flight=flight)
    try:
        assert router.handle({"op": "embed", "source": "x"})["ok"]
        deadline = time.time() + 5.0
        while flight.count == 0 and time.time() < deadline:
            time.sleep(0.01)
        records = flight.snapshot()
        assert records, "router flight recorder captured nothing"
        record = records[0]
        assert record["kind"] == "router"
        assert record["op"] == "embed" and record["slo_class"] == "embed"
        assert record["trace_id"]
        assert record["outcome"] == "ok"
        assert record["replica_slot"] == 0
        assert record["dispatch_wait_ms"] is not None
        assert "queue_depth_at_admission" in record
    finally:
        router.close()


def test_router_metrics_text_aggregates_with_replica_label():
    fakes = [MiniReplica(0), MiniReplica(1)]
    health = RuntimeHealth()
    router = make_router(fakes, health=health)
    try:
        for _ in range(6):
            assert router.handle({"op": "embed", "source": "x"})["ok"]
        # the prober's snapshots are the replica-side scrape source
        fakes[0].last_health = {
            "started_unix": 111.0, "snapshot_seq": 4,
            "counters": {"serve_requests": 4},
            "gauges": {"serve_queue_depth": 0},
            "latencies_ms": {
                "serve.e2e_ms": {"count": 4, "p50_ms": 1.0, "p90_ms": 2.0,
                                 "p99_ms": 3.0, "max_ms": 3.0,
                                 "mean_ms": 1.5, "sum_ms": 6.0},
            },
        }
        fakes[1].last_health = {
            "started_unix": 222.0, "snapshot_seq": 9,
            "counters": {"serve_requests": 2},
        }
        parsed = parse_prometheus_text(router.metrics_text())
        requests = {
            row["labels"].get("replica"): row["value"]
            for row in parsed["c2v_serve_requests_total"]
        }
        assert requests == {"r0": 4, "r1": 2}
        # router's own registry exports UNlabeled and agrees with health
        own = {
            row["labels"].get("replica"): row["value"]
            for row in parsed["c2v_serve_op_embed_requests_total"]
        }
        assert own[None] == 6
        assert own[None] == health.snapshot()["counters"][
            "serve.op.embed.requests"
        ]
        # per-replica start times make counter resets detectable
        starts = {
            row["labels"].get("replica"): row["value"]
            for row in parsed["c2v_process_start_time_seconds"]
        }
        assert starts["r0"] == 111.0 and starts["r1"] == 222.0
        assert parsed["c2v_serve_e2e_ms_sum"][0]["labels"] == {
            "replica": "r0"
        }
        # the burn gauges ride the same exposition
        assert "c2v_slo_embed_burn_rate" in parsed
        # and the health op carries the matching burn block
        payload = router.handle({"op": "health"})
        assert payload["fleet"]["slo_burn"]["embed"]["good"] >= 6
    finally:
        router.close()


def test_http_get_metrics_route():
    """GET /metrics on the HTTP transport: text/plain; version=0.0.4 that
    parses as exposition (stub server — the transport route itself)."""
    import urllib.request

    from code2vec_tpu.serve.protocol import make_http_server

    health = RuntimeHealth()
    health.counter("serve_requests").inc(3)

    class StubServer:
        shutdown_requested = False

        def handle(self, request):
            return {"ok": True}

        def metrics_text(self):
            return prometheus_text([({}, health.snapshot())])

    httpd = make_http_server(StubServer(), "127.0.0.1", 0)
    port = httpd.server_address[1]
    thread = threading.Thread(target=httpd.serve_forever, daemon=True)
    thread.start()
    try:
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics", timeout=30
        ) as resp:
            assert resp.status == 200
            content_type = resp.headers["Content-Type"]
            body = resp.read().decode("utf-8")
        assert content_type.startswith("text/plain")
        assert "version=0.0.4" in content_type
        parsed = parse_prometheus_text(body)
        assert parsed["c2v_serve_requests_total"][0]["value"] == 3
    finally:
        httpd.shutdown()
        httpd.server_close()
        thread.join(10)


# ---------------------------------------------------------------------------
# trace stitching (unit: two real tracers, synthetic span chain)
# ---------------------------------------------------------------------------


def test_stitch_traces_remaps_pids_and_indexes_trace_ids(tmp_path):
    router_tracer = Tracer(process_index=0, process_name="fleet-router")
    worker_tracer = Tracer(process_index=0, process_name="serve-worker-1")
    with router_tracer.span("fleet_request", category="fleet",
                            trace_id="t1", op="embed"):
        time.sleep(0.001)
    with worker_tracer.span("serve_request", category="serve",
                            trace_id="t1", op="embed"):
        with worker_tracer.span("serve_device", category="serve",
                                trace_ids=["t1", "t2"]):
            time.sleep(0.001)
    (tmp_path / "r0").mkdir()
    router_tracer.export(str(tmp_path / "trace-p0.json"))
    worker_tracer.export(str(tmp_path / "r0" / "trace-p0.json"))

    paths = trace_stitch.find_trace_files([str(tmp_path)])
    assert len(paths) == 2
    merged = trace_stitch.stitch_traces(paths)
    # both source processes got DISTINCT pids despite both exporting as 0
    pids = {
        e["pid"] for e in merged["traceEvents"] if e.get("ph") != "M"
    }
    assert len(pids) == 2
    names = {
        (e.get("args") or {}).get("name")
        for e in merged["traceEvents"]
        if e.get("ph") == "M" and e.get("name") == "process_name"
    }
    assert "fleet-router" in names
    assert "r0: serve-worker-1" in names
    index = trace_stitch.trace_index(merged)
    t1 = index["t1"]
    assert len(t1["processes"]) == 2  # the cross-process chain
    span_names = {s["name"] for s in t1["spans"]}
    assert span_names == {"fleet_request", "serve_request", "serve_device"}
    # the coalesce-aware link: t2 only rode the batched device span
    t2 = index["t2"]
    assert [s["name"] for s in t2["spans"]] == ["serve_device"]
    assert t2["spans"][0]["coalesced"] is True


def test_trace_stitch_cli(tmp_path):
    tracer = Tracer(process_index=0, process_name="solo")
    with tracer.span("serve_request", trace_id="cli-t"):
        pass
    tracer.export(str(tmp_path / "trace-p0.json"))
    out = tmp_path / "merged.json"
    index_out = tmp_path / "index.json"
    proc = subprocess.run(
        [sys.executable, os.path.join(TOOLS, "trace_stitch.py"),
         "--out", str(out), "--index-out", str(index_out), str(tmp_path)],
        capture_output=True, text=True, timeout=60,
    )
    assert proc.returncode == 0, proc.stderr
    summary = json.loads(proc.stdout)
    assert summary["files"] == 1 and summary["traces"] == 1
    merged = json.loads(out.read_text())
    assert any(
        e.get("name") == "serve_request" for e in merged["traceEvents"]
    )
    index = json.loads(index_out.read_text())
    assert "cli-t" in index


# ---------------------------------------------------------------------------
# the real thing: 2-replica subprocess fleet under a pipelined burst ->
# stitched trace with a complete router->replica->batcher->engine chain,
# /metrics agreement, burn accounting, flight dumps
# ---------------------------------------------------------------------------

PY = """
def add(a, b):
    total = a + b
    return total


def mul(a, b):
    product = a * b
    return product
"""


@pytest.fixture(scope="module")
def trained_tiny(tmp_path_factory):
    from code2vec_tpu.data.reader import load_corpus
    from code2vec_tpu.pyextract import extract_python_dataset
    from code2vec_tpu.train.config import TrainConfig
    from code2vec_tpu.train.loop import train

    root = tmp_path_factory.mktemp("obsfleet_py")
    src, ds, out = root / "src", root / "ds", root / "out"
    for d in (src, ds, out):
        d.mkdir()
    (src / "util.py").write_text(PY)
    extract_python_dataset(str(ds), str(src), [("util.py", "*")])
    data = load_corpus(
        ds / "corpus.txt", ds / "path_idxs.txt", ds / "terminal_idxs.txt"
    )
    cfg = TrainConfig(
        max_epoch=3, batch_size=2, encode_size=16, terminal_embed_size=8,
        path_embed_size=8, max_path_length=32, lr=0.01, print_sample_cycle=0,
    )
    train(cfg, data, out_dir=str(out))
    return ds, out


def test_fleet_trace_stitch_metrics_and_burn_end_to_end(
    trained_tiny, tmp_path
):
    """Boot a REAL 2-replica subprocess fleet with tracing + events on,
    push a pipelined embed burst through it, then assert the whole
    observability plane: stitched cross-process trace with a complete
    router -> replica(serve_request) -> batcher(serve_device) ->
    engine(engine_run) chain under one trace id, /metrics that parses and
    agrees with health counters on router AND replicas, SLO burn
    accounting with an intact budget, and worker flight_*.json dumps."""
    from code2vec_tpu.serve.fleet.__main__ import build_parser, build_router

    ds, out = trained_tiny
    trace_dir = tmp_path / "traces"
    events_dir = tmp_path / "events"
    args = build_parser().parse_args([
        "--replicas", "2",
        "--model_path", str(out),
        "--terminal_idx_path", str(ds / "terminal_idxs.txt"),
        "--path_idx_path", str(ds / "path_idxs.txt"),
        "--deadline_ms", "2",
        "--boot_timeout_s", "600",
        "--trace_dir", str(trace_dir),
        "--events_dir", str(events_dir),
        # every worker request leaves a flight record: the dump path is
        # part of what this scenario pins
        "--flight_threshold_ms", "0.0001",
    ])
    router_tracer = Tracer(process_index=0, process_name="fleet-router")
    previous_tracer = set_tracer(router_tracer)
    n_requests = 12
    try:
        router, events = build_router(args)
        try:
            # the fleet CLI rides the process-global registry — under the
            # full test session earlier suites have already counted ops
            # there, so every counter assertion below is a DELTA from here
            base = router.health.snapshot()["counters"].get(
                "serve.op.embed.requests", 0
            )
            # pipelined burst: submit everything, then resolve — the
            # fleet analogue of the stdio transport's coalescing loop
            resolvers = [
                router.handle_async({
                    "id": i, "op": "embed", "source": PY,
                    "language": "python", "method_name": "add",
                })
                for i in range(n_requests)
            ]
            payloads = [r() for r in resolvers]
            assert all(p.get("ok") for p in payloads), payloads[:2]
            assert [p["id"] for p in payloads] == list(range(n_requests))

            # ---- /metrics on the router: refresh the probe snapshots,
            # then scrape (lock-light: served from last_health)
            for slot in range(2):
                router._probe_slot(slot)
            parsed = parse_prometheus_text(router.metrics_text())
            per_replica = {
                row["labels"].get("replica"): row["value"]
                for row in parsed["c2v_serve_op_embed_requests_total"]
            }
            # router's own count covers the burst; the replicas' counts
            # (fresh subprocesses — no prior traffic) sum to it exactly
            # (placement split may be uneven)
            assert per_replica[None] - base == n_requests
            replica_total = sum(
                v for k, v in per_replica.items() if k is not None
            )
            assert replica_total == n_requests
            # agreement with the health op, per replica
            health_payload = router.handle({"op": "health"})
            for replica_row in health_payload["fleet"]["replicas"]:
                assert replica_row["alive"]
                assert replica_row["post_warmup_compiles"] == 0
            # replica-labeled start times present (reset detection)
            start_labels = {
                row["labels"].get("replica")
                for row in parsed["c2v_process_start_time_seconds"]
            }
            assert {"r0", "r1"} <= start_labels

            # ---- perf accounting (PR 17): every replica that served
            # traffic exports MFU from its compiled-cost + device-time
            # accountant, and the invariant achieved <= peak holds
            mfu_rows = {
                row["labels"].get("replica"): row["value"]
                for row in parsed["c2v_perf_mfu"]
            }
            assert {"r0", "r1"} <= set(mfu_rows), mfu_rows
            for replica, mfu in mfu_rows.items():
                assert 0.0 < mfu <= 1.0, (replica, mfu)
            peak_rows = {
                row["labels"].get("replica"): row["value"]
                for row in parsed["c2v_perf_peak_flops_per_s"]
            }
            for replica in ("r0", "r1"):
                achieved = [
                    row["value"]
                    for row in parsed["c2v_perf_achieved_flops_per_s"]
                    if row["labels"].get("replica") == replica
                ]
                assert achieved and achieved[0] <= peak_rows[replica]
            # build-info gauge on the router exposition (role=router),
            # jax-version label present without dragging jax into the
            # router process
            assert parsed["# types"]["c2v_build_info"] == "gauge"
            build_rows = parsed["c2v_build_info"]
            assert any(
                row["labels"].get("role") == "router" for row in build_rows
            )
            assert all(
                row["labels"].get("jax_version") for row in build_rows
            )

            # ---- fleet capacity block: per-rung device-ms/request rolled
            # into the max-QPS headroom signal (ROADMAP item 3)
            health_payload = router.handle({"op": "health"})
            capacity = health_payload["fleet"]["capacity"]
            assert capacity is not None, health_payload["fleet"]
            assert capacity["alive_replicas"] == 2
            assert capacity["requests_observed"] >= n_requests
            assert capacity["max_qps_fleet"] > 0
            assert capacity["max_qps_fleet"] == pytest.approx(
                capacity["max_qps_per_replica"] * 2, rel=1e-4
            )
            assert capacity["per_rung"], capacity
            for rung in capacity["per_rung"]:
                assert rung["device_ms_per_request"] > 0
                assert 0.0 < rung["share"] <= 1.0
            # replica health carries the full perf block the capacity
            # figure was derived from
            for replica_row in health_payload["fleet"]["replicas"]:
                perf = replica_row["perf"]
                assert perf["device_calls"] > 0
                assert perf["mfu"] == mfu_rows[f"r{replica_row['slot']}"]

            # ---- flights control op: live per-request breakdowns from
            # every replica plus the router's own recorder, no dump needed
            flights_payload = router.handle({"op": "flights"})
            assert flights_payload["ok"] is True
            assert len(flights_payload["replicas"]) == 2
            live_flights = [
                f for row in flights_payload["replicas"]
                for f in row.get("flights", [])
            ]
            assert live_flights, flights_payload["replicas"]
            assert all("device_ms" in f for f in live_flights)
            json.dumps(flights_payload)  # wire-safe end to end

            # ---- burn accounting: a clean burst leaves the budget alone
            burn = health_payload["fleet"]["slo_burn"]["embed"]
            assert burn["good"] == n_requests and burn["bad"] == 0
            assert burn["exhausted"] is False
            assert health_payload["fleet"]["flight_recorded"] is not None
        finally:
            # graceful close: workers drain, exit 0, and WRITE their
            # trace files + flight dumps on the way out
            router.close()
            if events is not None:
                events.close()
    finally:
        set_tracer(previous_tracer)
    router_tracer.export_dir(str(trace_dir))

    # ---- worker flight dumps survived the processes
    flight_files = glob.glob(
        str(events_dir / "r*" / "flight" / "flight_*.json")
    )
    assert flight_files, "no worker flight_*.json dumps found"
    record = json.loads(open(flight_files[0]).read())
    assert record["kind"] == "serve" and record["trace_id"]
    assert "device_ms" in record and "queue_wait_ms" in record

    # ---- stitch: 3 per-process files -> one valid Chrome trace
    paths = trace_stitch.find_trace_files([str(trace_dir)])
    assert len(paths) == 3, paths  # router + 2 replicas
    merged = trace_stitch.stitch_traces(paths)
    data_events = [
        e for e in merged["traceEvents"] if e.get("ph") != "M"
    ]
    assert all("ts" in e and "pid" in e for e in data_events)
    assert len({e["pid"] for e in data_events}) >= 2
    # valid Chrome trace: serializes, events time-ordered
    json.dumps(merged)
    ts = [e["ts"] for e in data_events]
    assert ts == sorted(ts)

    # ---- the acceptance chain: >= 1 sampled request whose spans cross
    # router -> replica -> batcher -> engine under ONE trace id
    index = trace_stitch.trace_index(merged)
    required = {"fleet_request", "serve_request", "serve_device",
                "engine_run"}
    complete = [
        trace_id for trace_id, entry in index.items()
        if required <= {s["name"] for s in entry["spans"]}
        and len(entry["processes"]) >= 2
    ]
    assert complete, (
        f"no complete router->replica->batcher->engine chain; saw "
        f"{ {t: sorted({s['name'] for s in e['spans']}) for t, e in list(index.items())[:4]} }"
    )
    # the chain's worker spans all live in ONE replica's file
    entry = index[complete[0]]
    worker_processes = {
        s["process"] for s in entry["spans"] if s["name"] != "fleet_request"
    }
    assert len(worker_processes) == 1
    assert next(iter(worker_processes)).startswith("r")
