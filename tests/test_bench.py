"""bench.py helper coverage: the benchmark feedback loop and the
wedged-tunnel fallback decision.

These guard the two historical bench failure modes the round-2 verdict
called out: `vs_baseline` silently stuck at 1.0 because prior rounds were
read through the wrong schema (Weak #1), and a wedged TPU tunnel producing
rc=1 with zero perf data because init hangs rather than raises (Weak #2).
The fallback tests monkeypatch the killable subprocess probe so no real
backend is touched; the suite runs under the conftest CPU platform either
way.
"""

import importlib.util
import json
import os
import os as bench_os  # alias: the name monkeypatched for _kill_tree's killpg
import subprocess
import sys

import pytest

_BENCH_PATH = os.path.join(os.path.dirname(__file__), "..", "bench.py")


@pytest.fixture(scope="module")
def bench():
    spec = importlib.util.spec_from_file_location("_bench_under_test", _BENCH_PATH)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


class TestExtractMetric:
    def test_bare_value_payload(self, bench):
        assert bench._extract_metric({"value": 123.5}) == (123.5, None)

    def test_driver_parsed_schema(self, bench):
        payload = {"rc": 0, "parsed": {"metric": "ctx/s", "value": 6955073}}
        assert bench._extract_metric(payload) == (6955073.0, None)

    def test_tail_scan_takes_last_metric_line(self, bench):
        tail = "\n".join(
            [
                "noise",
                json.dumps({"detail": "not the metric"}),
                json.dumps({"metric": "ctx/s", "value": 42.0, "backend": "tpu"}),
            ]
        )
        assert bench._extract_metric({"rc": 0, "tail": tail}) == (42.0, "tpu")

    def test_backend_from_detail_line(self, bench):
        # BENCH_r02 shape: metric line without a backend field, detail line
        # (with backend) printed after it
        tail = "\n".join(
            [
                json.dumps({"metric": "ctx/s", "value": 6955072.6}),
                json.dumps({"detail": {"backend": "tpu", "steps_per_sec": 33.96}}),
            ]
        )
        assert bench._extract_metric({"rc": 0, "tail": tail}) == (6955072.6, "tpu")

    def test_non_numeric_and_missing_value(self, bench):
        assert bench._extract_metric({"parsed": {"value": None}}) is None
        assert bench._extract_metric({"parsed": {"value": "n/a"}}) is None
        assert bench._extract_metric({"rc": 0, "tail": "no json here"}) is None
        assert bench._extract_metric({}) is None


class TestPreviousBenchmark:
    def _write(self, tmp_path, name, payload):
        (tmp_path / name).write_text(json.dumps(payload))

    def test_newest_successful_round_wins(self, bench, tmp_path, monkeypatch):
        self._write(tmp_path, "BENCH_r01.json", {"rc": 1, "parsed": {"value": 1.0}})
        self._write(tmp_path, "BENCH_r02.json", {"rc": 0, "parsed": {"value": 2.0}})
        self._write(tmp_path, "BENCH_r03.json", {"rc": 0, "parsed": {"value": 3.0}})
        monkeypatch.setattr(
            bench.glob,
            "glob",
            lambda pattern: [str(p) for p in tmp_path.glob("BENCH_r*.json")],
        )
        assert bench._previous_benchmark("tpu") == (3.0, True)

    def test_failed_and_valueless_rounds_skipped(self, bench, tmp_path, monkeypatch):
        self._write(tmp_path, "BENCH_r01.json", {"rc": 0, "parsed": {"value": 5.0}})
        self._write(tmp_path, "BENCH_r02.json", {"rc": 1, "parsed": {"value": 9.0}})
        self._write(tmp_path, "BENCH_r03.json", {"rc": 0, "parsed": {"detail": "x"}})
        (tmp_path / "BENCH_r04.json").write_text("{corrupt")
        monkeypatch.setattr(
            bench.glob,
            "glob",
            lambda pattern: [str(p) for p in tmp_path.glob("BENCH_r*.json")],
        )
        assert bench._previous_benchmark("tpu") == (5.0, True)

    def test_cpu_fallback_round_cannot_poison_device_baseline(
        self, bench, tmp_path, monkeypatch
    ):
        # a wedged-tunnel round lands a (labeled) CPU number; the next
        # healthy device run must still compare against the last DEVICE
        # round, or vs_baseline becomes a meaningless ~2000x
        self._write(
            tmp_path,
            "BENCH_r02.json",
            {"rc": 0, "parsed": {"value": 6955072.6, "backend": "tpu"}},
        )
        self._write(
            tmp_path,
            "BENCH_r03.json",
            {"rc": 0, "parsed": {"value": 103955.6, "backend": "cpu"}},
        )
        monkeypatch.setattr(
            bench.glob,
            "glob",
            lambda pattern: [str(p) for p in tmp_path.glob("BENCH_r*.json")],
        )
        assert bench._previous_benchmark("tpu") == (6955072.6, True)
        # and a cpu run compares like-for-like against the cpu round
        assert bench._previous_benchmark("cpu") == (103955.6, True)

    def test_unlabeled_round_counts_as_device(self, bench, tmp_path, monkeypatch):
        self._write(tmp_path, "BENCH_r02.json", {"rc": 0, "parsed": {"value": 7.0}})
        monkeypatch.setattr(
            bench.glob,
            "glob",
            lambda pattern: [str(p) for p in tmp_path.glob("BENCH_r*.json")],
        )
        assert bench._previous_benchmark("tpu") == (7.0, True)
        assert bench._previous_benchmark("cpu") is None

    def test_no_prior_rounds(self, bench, monkeypatch):
        monkeypatch.setattr(bench.glob, "glob", lambda pattern: [])
        assert bench._previous_benchmark("tpu") is None

    def test_post_honesty_round_flagged_as_real_accounting(
        self, bench, tmp_path, monkeypatch
    ):
        # a round whose record carries pad_efficiency stored a REAL-context
        # headline; vs_baseline must divide real contexts into it, while a
        # pre-change round (no pad_efficiency anywhere) gets padded slots
        self._write(
            tmp_path,
            "BENCH_r06.json",
            {
                "rc": 0,
                "parsed": {"value": 9.0, "backend": "tpu"},
                "tail": '{"detail": {"backend": "tpu", "pad_efficiency": 0.61}}',
            },
        )
        monkeypatch.setattr(
            bench.glob,
            "glob",
            lambda pattern: [str(p) for p in tmp_path.glob("BENCH_r*.json")],
        )
        assert bench._previous_benchmark("tpu") == (9.0, False)


class TestInitBackendFallback:
    """The fallback *decision* logic, with the subprocess probe stubbed.

    The real probe compiles + executes a tiny jit in a killable subprocess
    because a wedged axon tunnel has been observed to hang on the first
    dispatch while `jax.devices()` still answers — an in-process attempt
    would stall the whole benchmark past the driver's window.
    """

    def test_wedged_probe_falls_back_to_cpu(self, bench, monkeypatch):
        monkeypatch.delenv("JAX_PLATFORMS", raising=False)
        probes = []
        monkeypatch.setattr(
            bench, "_probe_default_backend", lambda t: probes.append(t) or False
        )
        monkeypatch.setattr(bench.time, "sleep", lambda s: None)
        _jax, backend, fell_back = bench._init_backend()
        assert fell_back is True
        assert backend == "cpu"
        assert os.environ["JAX_PLATFORMS"] == "cpu"
        assert len(probes) == 2  # one retry before giving up on the tunnel

    def test_healthy_probe_keeps_default_backend(self, bench, monkeypatch):
        monkeypatch.delenv("JAX_PLATFORMS", raising=False)
        monkeypatch.setattr(bench, "_probe_default_backend", lambda t: True)
        _jax, backend, fell_back = bench._init_backend()
        assert fell_back is False
        # under the test harness the default backend IS cpu; the point is
        # that no fallback was recorded and the env was left alone
        assert "JAX_PLATFORMS" not in os.environ or os.environ["JAX_PLATFORMS"] == ""

    def test_cpu_platform_skips_probe(self, bench, monkeypatch):
        monkeypatch.setenv("JAX_PLATFORMS", "cpu")

        def _boom(t):  # pragma: no cover - failure is the assertion
            raise AssertionError("probe must not run for an explicit cpu platform")

        monkeypatch.setattr(bench, "_probe_default_backend", _boom)
        _jax, backend, fell_back = bench._init_backend()
        assert fell_back is False
        assert backend == "cpu"

    def test_ambient_device_platform_is_probed(self, bench, monkeypatch):
        # the harness exports JAX_PLATFORMS=axon ambiently — that must NOT
        # read as an operator pin, or the wedge guard never fires in the
        # exact environment it was built for
        monkeypatch.setenv("JAX_PLATFORMS", "axon")
        monkeypatch.setattr(bench, "_probe_default_backend", lambda t: False)
        monkeypatch.setattr(bench.time, "sleep", lambda s: None)
        _jax, backend, fell_back = bench._init_backend()
        assert fell_back is True
        assert backend == "cpu"
        assert os.environ["JAX_PLATFORMS"] == "cpu"

    def test_fell_back_env_marks_emergency_recipe(self, bench, monkeypatch):
        # the supervisor's CPU retry sets both vars; the child must report
        # fell_back=True so the reduced emergency recipe kicks in
        monkeypatch.setenv("JAX_PLATFORMS", "cpu")
        monkeypatch.setenv("BENCH_FELL_BACK", "1")
        _jax, backend, fell_back = bench._init_backend()
        assert fell_back is True
        assert backend == "cpu"

    def test_probe_timeout_env_respected(self, bench, monkeypatch):
        monkeypatch.delenv("JAX_PLATFORMS", raising=False)
        monkeypatch.setenv("BENCH_INIT_TIMEOUT", "7")
        seen = []
        monkeypatch.setattr(
            bench, "_probe_default_backend", lambda t: seen.append(t) or True
        )
        bench._init_backend()
        assert seen == [7.0]


class _FakeProc:
    def __init__(self, rc, hang=False):
        self._rc = rc
        self._hang = hang
        self.killed = False
        self.pid = -1  # never passed to a real killpg (stubbed in _patch_popen)

    def wait(self, timeout=None):
        import subprocess

        if self._hang and not self.killed:
            raise subprocess.TimeoutExpired(cmd="bench", timeout=timeout)
        return self._rc

    def kill(self):
        self.killed = True


class TestSupervisor:
    """_supervise(): the killable-child harness that defends against the
    post-init hang (probe passes, first compile wedges — observed live on
    the axon tunnel, 2026-07-30)."""

    def _patch_popen(self, monkeypatch, procs, envs):
        import subprocess

        it = iter(procs)

        def fake_popen(cmd, env=None, **kwargs):
            envs.append(env)
            return next(it)

        monkeypatch.setattr(subprocess, "Popen", fake_popen)

        # route _kill_tree's killpg to the fallback .kill() path instead of
        # letting a fake pid reach the real syscall
        def fake_killpg(pgid, sig):
            raise ProcessLookupError(pgid)

        monkeypatch.setattr(bench_os, "killpg", fake_killpg)

    def test_healthy_child_single_attempt(self, bench, monkeypatch):
        monkeypatch.delenv("JAX_PLATFORMS", raising=False)
        envs = []
        self._patch_popen(monkeypatch, [_FakeProc(0)], envs)
        assert bench._supervise() == 0
        assert len(envs) == 1
        assert envs[0]["BENCH_SUPERVISED"] == "1"
        assert "BENCH_FELL_BACK" not in envs[0]

    def test_stale_fell_back_export_stripped_from_device_attempt(
        self, bench, monkeypatch
    ):
        # a leftover BENCH_FELL_BACK=1 export must not put a healthy device
        # attempt on the reduced emergency recipe
        monkeypatch.delenv("JAX_PLATFORMS", raising=False)
        monkeypatch.setenv("BENCH_FELL_BACK", "1")
        envs = []
        self._patch_popen(monkeypatch, [_FakeProc(0)], envs)
        assert bench._supervise() == 0
        assert "BENCH_FELL_BACK" not in envs[0]

    def test_hung_child_killed_then_cpu_retry(self, bench, monkeypatch):
        monkeypatch.delenv("JAX_PLATFORMS", raising=False)
        envs = []
        hung = _FakeProc(0, hang=True)
        self._patch_popen(monkeypatch, [hung, _FakeProc(0)], envs)
        assert bench._supervise() == 0
        assert hung.killed
        assert envs[1]["JAX_PLATFORMS"] == "cpu"
        assert envs[1]["BENCH_FELL_BACK"] == "1"

    def test_failing_child_cpu_retry(self, bench, monkeypatch):
        monkeypatch.delenv("JAX_PLATFORMS", raising=False)
        envs = []
        self._patch_popen(monkeypatch, [_FakeProc(3), _FakeProc(0)], envs)
        assert bench._supervise() == 0
        assert envs[1]["JAX_PLATFORMS"] == "cpu"

    def test_both_attempts_fail_emits_contract_line(self, bench, monkeypatch, capsys):
        monkeypatch.delenv("JAX_PLATFORMS", raising=False)
        envs = []
        self._patch_popen(monkeypatch, [_FakeProc(1), _FakeProc(1)], envs)
        assert bench._supervise() == 1
        last = capsys.readouterr().out.strip().splitlines()[-1]
        obj = json.loads(last)
        assert obj["metric"] == "path_contexts_per_sec_per_chip"
        assert obj["value"] is None
        assert "error" in obj

    def test_cpu_platform_skips_cpu_retry(self, bench, monkeypatch, capsys):
        # already on cpu: a cpu retry would repeat the same failure
        monkeypatch.setenv("JAX_PLATFORMS", "cpu")
        envs = []
        self._patch_popen(monkeypatch, [_FakeProc(1)], envs)
        assert bench._supervise() == 1
        assert len(envs) == 1
        assert json.loads(capsys.readouterr().out.strip().splitlines()[-1])["value"] is None

    def test_ambient_device_platform_still_gets_cpu_retry(self, bench, monkeypatch):
        # JAX_PLATFORMS=axon is exported by the harness itself; a hung
        # device attempt must still produce a labeled cpu number
        monkeypatch.setenv("JAX_PLATFORMS", "axon")
        envs = []
        hung = _FakeProc(0, hang=True)
        self._patch_popen(monkeypatch, [hung, _FakeProc(0)], envs)
        assert bench._supervise() == 0
        assert hung.killed
        assert envs[1]["JAX_PLATFORMS"] == "cpu"
        assert envs[1]["BENCH_FELL_BACK"] == "1"

    def test_no_fallback_opt_out(self, bench, monkeypatch, capsys):
        monkeypatch.setenv("JAX_PLATFORMS", "axon")
        monkeypatch.setenv("BENCH_NO_FALLBACK", "1")
        envs = []
        self._patch_popen(monkeypatch, [_FakeProc(1)], envs)
        assert bench._supervise() == 1
        assert len(envs) == 1
        assert json.loads(capsys.readouterr().out.strip().splitlines()[-1])["value"] is None

    def test_deadline_env_respected(self, bench, monkeypatch):
        monkeypatch.setenv("JAX_PLATFORMS", "cpu")
        monkeypatch.setenv("BENCH_DEADLINE", "17")
        seen = []

        class _Proc(_FakeProc):
            def wait(self, timeout=None):
                seen.append(timeout)
                return 0

        envs = []
        self._patch_popen(monkeypatch, [_Proc(0)], envs)
        assert bench._supervise() == 0
        # single (final) attempt gets the whole remaining budget
        assert len(seen) == 1 and 16.0 < seen[0] <= 17.0

    def test_malformed_deadline_does_not_crash(self, bench, monkeypatch):
        monkeypatch.setenv("JAX_PLATFORMS", "cpu")
        monkeypatch.setenv("BENCH_DEADLINE", "20m")
        envs = []
        self._patch_popen(monkeypatch, [_FakeProc(0)], envs)
        assert bench._supervise() == 0  # fell back to the 1200s default

    def test_first_attempt_reserves_budget_for_cpu_retry(self, bench, monkeypatch):
        monkeypatch.delenv("JAX_PLATFORMS", raising=False)
        monkeypatch.setenv("BENCH_DEADLINE", "1000")
        seen = []

        class _Proc(_FakeProc):
            def wait(self, timeout=None):
                if timeout is not None:  # ignore the post-kill reap
                    seen.append(timeout)
                return super().wait(timeout=timeout)

        hung = _Proc(0, hang=True)
        ok = _Proc(0)
        envs = []
        self._patch_popen(monkeypatch, [hung, ok], envs)
        assert bench._supervise() == 0
        # attempt 1 is held back from the full budget (1000 - min(420, 500));
        # the final attempt gets everything left of the TOTAL budget (the
        # fakes consume no wall-clock, so that is still ~1000 here)
        assert len(seen) == 2
        assert 570.0 < seen[0] <= 580.0
        assert 990.0 < seen[1] <= 1000.0

    def test_no_fallback_raise_path_raises_instead_of_cpu(self, bench, monkeypatch):
        # with the opt-out set, init that RAISES must surface the failure
        # (-> error JSON line), not silently measure CPU
        monkeypatch.setenv("JAX_PLATFORMS", "tpu")
        monkeypatch.setenv("BENCH_NO_FALLBACK", "1")
        monkeypatch.delenv("BENCH_FELL_BACK", raising=False)
        monkeypatch.setattr(bench.time, "sleep", lambda s: None)
        # the real purge would evict jax from sys.modules and poison every
        # later test in the suite (stale cross-module references); the
        # decision under test is the no-fallback raise, not the purge
        monkeypatch.setattr(bench, "_purge_jax_modules", lambda: None)

        import builtins

        real_import = builtins.__import__

        def failing_import(name, *args, **kwargs):
            if name == "jax":
                raise RuntimeError("no backend")
            return real_import(name, *args, **kwargs)

        monkeypatch.setattr(builtins, "__import__", failing_import)
        with pytest.raises(RuntimeError, match="BENCH_NO_FALLBACK"):
            bench._init_backend()


class TestMeshKnobSmoke:
    """One real bench.py run on the virtual 8-device CPU mesh, exercising
    the mesh knobs (BENCH_DATA_AXIS × BENCH_CTX_AXIS — VERDICT r3 #4's
    ctx knob) together with the streaming attention lowering
    (BENCH_ATTN_IMPL). Subprocess: bench must force its own platform/mesh
    from env, as the driver invokes it."""

    @pytest.mark.slow
    def test_ctx_axis_and_streaming_attn(self):
        env = dict(
            # scrub ambient BENCH_* knobs: an outer BENCH_MODEL_AXIS (or a
            # malformed BENCH_ADAM_MU_DTYPE, which now raises) must not
            # leak into the measurement under test
            {k: v for k, v in os.environ.items() if not k.startswith("BENCH_")},
            JAX_PLATFORMS="cpu",
            XLA_FLAGS=(os.environ.get("XLA_FLAGS", "")
                       + " --xla_force_host_platform_device_count=8").strip(),
            BENCH_SUPERVISED="1",  # measurement directly, no supervisor child
            BENCH_DATA_AXIS="2",
            BENCH_CTX_AXIS="2",
            BENCH_ATTN_IMPL="streaming",
            BENCH_BATCH="16",
            BENCH_BAG="8",
            BENCH_STEPS="2",
            BENCH_CHUNK="1",
            BENCH_WARMUP_CHUNKS="1",
        )
        out = subprocess.run(
            [sys.executable, _BENCH_PATH], env=env, capture_output=True,
            text=True, timeout=600,
            cwd=os.path.dirname(_BENCH_PATH) or ".",
        )
        assert out.returncode == 0, out.stderr[-2000:]
        lines = [l for l in out.stdout.splitlines() if l.startswith("{")]
        metric = next(
            json.loads(l) for l in reversed(lines)
            if '"metric"' in l and '"path_contexts_per_sec_per_chip"' in l
        )
        assert metric["value"] > 0
        assert metric["backend"] == "cpu"
        err_lines = [l for l in out.stderr.splitlines() if l.startswith("{")]
        detail = next(  # the detail record goes to stderr (driver contract:
            # stdout's last JSON line is the metric)
            json.loads(l)["detail"]
            for l in reversed(lines + err_lines)
            if '"detail"' in l
        )
        assert detail["mesh"] == {"data": 2, "model": 1, "ctx": 2}
