"""Real multi-process jax.distributed integration (SURVEY.md §5.8, §7.4).

Everything else in the suite exercises the multi-host code paths inside ONE
process (jax.process_count() == 1 shortcuts). This test spawns two actual
processes that form a distributed group over the CPU backend and drive the
production host-sharded feed: each loads half the corpus, training runs
with the batch data-sharded across both processes' devices, and the final
metrics must agree bit-for-bit between processes (they observe the same
global computation).
"""

import json
import os
import socket
import subprocess
import sys

import pytest

WORKER = os.path.join(os.path.dirname(__file__), "mp_worker.py")
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _run_group(tmp_path, n_procs: int, extra_env: dict | None = None) -> dict:
    """Spawn an n-process jax.distributed group; return {pid: result_json}."""
    port = _free_port()
    procs = []
    for pid in range(n_procs):
        env = os.environ.copy()
        env.update(
            COORDINATOR_ADDRESS=f"127.0.0.1:{port}",
            NUM_PROCESSES=str(n_procs),
            PROCESS_ID=str(pid),
            PYTHONPATH=REPO,
            **(extra_env or {}),
        )
        # the worker pins its own XLA_FLAGS/JAX_PLATFORMS before importing jax
        env.pop("XLA_FLAGS", None)
        ds = tmp_path / f"ds{pid}"
        out = tmp_path / "out"  # shared: orbax multihost commit needs one dir
        ds.mkdir()
        out.mkdir(exist_ok=True)
        # file-backed output: pipes would (a) lose the worker's faulthandler
        # stall dump when the parent times out and (b) risk a pipe-buffer
        # stall coupling back into the workers' lockstep collectives
        log = open(tmp_path / f"worker{pid}.log", "w+", encoding="utf-8")
        procs.append(
            (
                subprocess.Popen(
                    [sys.executable, WORKER, str(ds), str(out)],
                    stdout=log,
                    stderr=subprocess.STDOUT,
                    cwd=REPO,
                    env=env,
                ),
                log,
            )
        )
    try:
        for p, _ in procs:
            try:
                p.wait(timeout=600)
            except subprocess.TimeoutExpired:
                pass
    finally:
        for p, _ in procs:
            if p.poll() is None:
                p.kill()
                p.wait()
    results = {}
    for p, log in procs:
        log.flush()
        log.seek(0)
        out = log.read()
        log.close()
        assert p.returncode == 0, f"worker failed:\n{out[-4000:]}"
        last = [ln for ln in out.splitlines() if ln.startswith("{")][-1]
        r = json.loads(last)
        results[r["process"]] = r
    assert set(results) == set(range(n_procs))
    return results


def _assert_lockstep(results: dict, n_procs: int) -> None:
    # every process ran the same global computation: identical trajectories
    for pid in range(1, n_procs):
        assert results[pid]["losses"] == results[0]["losses"]
        assert results[pid]["f1s"] == results[0]["f1s"]
        assert results[pid]["best_f1"] == results[0]["best_f1"]
    assert len(results[0]["losses"]) == 3
    assert all(l > 0 for l in results[0]["losses"])


@pytest.mark.slow
def test_two_process_host_sharded_training(tmp_path):
    results = _run_group(tmp_path, 2)
    _assert_lockstep(results, 2)


@pytest.mark.slow
def test_two_process_sharded_staging(tmp_path):
    """feed_groups x ShardedStagedCorpus, cross-process (VERDICT r4 weak
    #5): 2 processes x 2 local devices, mesh data=4 — each process loads
    and host-stages ONLY its feed group's corpus shard (~half the items),
    `shard_staged_multiprocess` assembles the global [4, ...] staged
    arrays from process-local blocks, and ShardedEpochRunner trains
    scanned chunks over the cross-process mesh in lockstep."""
    results = _run_group(
        tmp_path,
        2,
        extra_env=dict(MP_SHARD_STAGED="1", MP_DATA_AXIS="4"),
    )
    for pid in range(2):
        r = results[pid]
        assert r["n_groups"] == 2
        # the host staged only its shard, not the 96-item corpus
        assert r["local_items"] < 96
        assert r["local_staged_items"] == r["local_items"]
        assert r["global_items"] == 96
    assert results[0]["feed_group"] != results[1]["feed_group"]
    assert results[0]["local_items"] + results[1]["local_items"] == 96
    _assert_lockstep(results, 2)


@pytest.mark.slow
def test_four_process_tensor_parallel_training(tmp_path):
    """4 processes x 1 device, mesh data=2 x model=2: with one device per
    process each model pair straddles TWO processes, so the row-sharded
    embedding gathers' psum and the column-sharded head's collectives run
    cross-process over the Gloo backend — the NCCL-replacement obligation
    of SURVEY §5.8 exercised end-to-end."""
    results = _run_group(
        tmp_path,
        4,
        extra_env=dict(
            MP_LOCAL_DEVICES="1", MP_DATA_AXIS="2", MP_MODEL_AXIS="2"
        ),
    )
    _assert_lockstep(results, 4)
