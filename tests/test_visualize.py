"""Visualization-layer tests (reference parity: visualize_code_vec.py)."""

import numpy as np
import pytest

from code2vec_tpu.formats.vectors_io import (
    append_code_vectors,
    write_code_vectors_header,
)
from code2vec_tpu.visualize import visualize_code_vectors, write_projector_tsv


@pytest.fixture()
def code_vec(tmp_path):
    path = tmp_path / "code.vec"
    vectors = np.asarray([[0.5, -1.25, 3.0], [1.0, 2.0, -0.5]], np.float32)
    write_code_vectors_header(path, 2, 3)
    append_code_vectors(path, ["getName", "setValue"], vectors)
    return path, vectors


class TestProjectorTSV:
    def test_round_trip(self, tmp_path, code_vec):
        path, vectors = code_vec
        out = visualize_code_vectors(path, tmp_path / "runs")
        loaded = np.loadtxt(out["vectors"], delimiter="\t")
        np.testing.assert_allclose(loaded, vectors)
        labels = (tmp_path / "runs" / "metadata.tsv").read_text().splitlines()
        assert labels == ["getName", "setValue"]
        config = (tmp_path / "runs" / "projector_config.pbtxt").read_text()
        assert "vectors.tsv" in config and "metadata.tsv" in config

    def test_labels_with_tabs_sanitized(self, tmp_path):
        out = write_projector_tsv(
            tmp_path, ["a\tb"], np.zeros((1, 2), np.float32))
        assert (tmp_path / "metadata.tsv").read_text() == "a b\n"

    def test_cli_entry(self, tmp_path, code_vec):
        from code2vec_tpu.visualize import main

        path, _ = code_vec
        main([str(path), "--log_dir", str(tmp_path / "viz")])
        assert (tmp_path / "viz" / "vectors.tsv").exists()
