"""Differential test: our corpus reader vs the REFERENCE's DatasetReader.

`data/reader.py` claims to mirror the reference's corpus semantics
(index shifts, label-vocab insertion order, alias normalization,
variable-index discovery) bit-for-bit — every checkpoint import and every
F1 comparison rests on that. These tests load the reference's actual
`DatasetReader` from /root/reference (skipped when absent) and run both
readers over randomly generated corpora, comparing every field: vocab
mappings, per-item context triples in order, label indices, aliases, and
the `@var_*` terminal index list. Covers all three task-flag
combinations and labels that normalize to the empty string.
"""

import os

import numpy as np
import pytest

from conftest import import_reference

ReferenceReader = import_reference("model.dataset_reader").DatasetReader

from conftest import make_reference_corpus  # noqa: E402

from code2vec_tpu.data.reader import load_corpus  # noqa: E402

# label pool deliberately includes repeats-by-normalization ("getValue2" and
# "getValue" collide), caps runs, and names that normalize to ""
_LABELS = [
    "getValue", "getValue2", "get_value", "toString", "HTMLParser",
    "a", "_", "_123", "parseHTTPResponse", "snake_case_name", "X",
]
_ORIGINALS = ["userName", "i", "HTTPClient", "temp_1", "x2", "_private"]


def _random_corpus(tmp_path, rng):
    return make_reference_corpus(
        tmp_path, rng,
        n_methods=25, n_terminals=30, n_paths=40, n_vars=5,
        label_fn=lambda i, r: str(r.choice(_LABELS)),
        alias_fn=lambda i, v, r: str(r.choice(_ORIGINALS)),
    )


def _compare(ours, theirs):
    # vocab mappings, not just sizes
    assert ours.terminal_vocab.stoi == theirs.terminal_vocab.stoi
    assert ours.path_vocab.stoi == theirs.path_vocab.stoi
    # label vocab: identical insertion order -> identical index mapping
    assert ours.label_vocab.itos == theirs.label_vocab.itos
    # @var_* terminal ids (order-insensitive: theirs follows dict order)
    assert sorted(int(v) for v in ours.variable_indexes) == sorted(
        theirs.variable_indexes
    )
    assert ours.n_items == len(theirs.items)
    for i, item in enumerate(theirs.items):
        lo, hi = ours.row_splits[i], ours.row_splits[i + 1]
        our_triples = list(
            zip(
                (int(x) for x in ours.starts[lo:hi]),
                (int(x) for x in ours.paths[lo:hi]),
                (int(x) for x in ours.ends[lo:hi]),
            )
        )
        assert our_triples == item.path_contexts, f"item {i} contexts"
        assert int(ours.ids[i]) == item.id
        assert ours.normalized_labels[i] == item.normalized_label
        assert ours.sources[i] == item.source, f"item {i} source"
        assert ours.aliases[i] == item.aliases, f"item {i} aliases"
        if ours.infer_method:
            assert (
                int(ours.labels[i])
                == theirs.label_vocab.stoi[item.normalized_label]
            )


@pytest.mark.parametrize(
    "infer_method,infer_variable",
    [(True, False), (True, True), (False, True)],
    ids=["method", "method+variable", "variable-only"],
)
@pytest.mark.parametrize("seed", [0, 1, 2])
def test_reader_matches_reference(tmp_path, seed, infer_method, infer_variable):
    rng = np.random.default_rng(seed)
    corpus, path_idx, terminal_idx = _random_corpus(tmp_path, rng)

    theirs = ReferenceReader(
        str(corpus), str(path_idx), str(terminal_idx),
        infer_method=infer_method, infer_variable=infer_variable,
        shuffle_variable_indexes=False,
    )
    # python parser: the portable path
    ours_py = load_corpus(
        corpus, path_idx, terminal_idx,
        infer_method=infer_method, infer_variable=infer_variable,
        cache=False, native=False,
    )
    _compare(ours_py, theirs)
    # native C++ parser — skipped (not silently downgraded) when the
    # library isn't built, so this leg can never pass vacuously via
    # load_corpus's python fallback
    import code2vec_tpu.extractor as ex

    if not os.path.exists(ex.LIBRARY):
        pytest.skip("native extractor library not built")
    ours_native = load_corpus(
        corpus, path_idx, terminal_idx,
        infer_method=infer_method, infer_variable=infer_variable,
        cache=False, native=True,
    )
    _compare(ours_native, theirs)
