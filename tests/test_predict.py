"""Single-source inference (code2vec_tpu.predict): checkpoint + vocab
metadata -> top-k method-name predictions for new source."""

import json
import os

import numpy as np
import pytest

from code2vec_tpu.data.reader import load_corpus
from code2vec_tpu.extractor import build_extractor, extract_dataset
from code2vec_tpu.predict import Predictor, main as predict_main
from code2vec_tpu.train.config import TrainConfig
from code2vec_tpu.train.loop import train

JAVA = """
class Util {
  int add(int a, int b) { int total = a + b; return total; }
  int mul(int a, int b) { int product = a * b; return product; }
  boolean isEven(int n) { boolean even = n % 2 == 0; return even; }
  int addChecked(int a, int b) { if (a > 0 && b > 0) { return a + b; } return 0; }
  int mulTwice(int a, int b) { int product = a * b * 2; return product; }
  boolean isEvenOrZero(int n) { boolean even = n % 2 == 0 || n == 0; return even; }
}
"""


def _train_on_util(tmp_path_factory, name, infer_variable=False, epochs=25):
    """Extract JAVA into a fresh dataset dir and train the given task."""
    build_extractor()
    root = tmp_path_factory.mktemp(name)
    src = root / "src"
    ds = root / "ds"
    out = root / "out"
    for d in (src, ds, out):
        d.mkdir()
    (src / "Util.java").write_text(JAVA)
    (ds / "methods.txt").write_text("Util.java\t*\n")
    extract_dataset(str(ds), str(src))
    data = load_corpus(
        ds / "corpus.txt", ds / "path_idxs.txt", ds / "terminal_idxs.txt",
        infer_method=not infer_variable, infer_variable=infer_variable,
    )
    cfg = TrainConfig(
        max_epoch=epochs, batch_size=4, encode_size=48,
        terminal_embed_size=24, path_embed_size=24, max_path_length=64,
        lr=0.01, print_sample_cycle=0,
        infer_method_name=not infer_variable,
        infer_variable_name=infer_variable,
    )
    train(cfg, data, out_dir=str(out))
    return ds, out


@pytest.fixture(scope="module")
def trained(tmp_path_factory):
    return _train_on_util(tmp_path_factory, "predict")


def test_meta_persisted(trained):
    ds, out = trained
    meta = json.loads((out / "model_meta.json").read_text())
    assert meta["max_path_length"] == 64 and meta["infer_method_name"]
    assert (out / "label_vocab.txt").exists()


def test_predicts_memorized_methods(trained):
    ds, out = trained
    p = Predictor(str(out), str(ds / "terminal_idxs.txt"), str(ds / "path_idxs.txt"))
    results = p.predict_source(JAVA, "*", top_k=3)
    assert len(results) == 6
    hits = 0
    for m in results:
        names = [pr.name for pr in m.predictions]
        expected = m.method_name.lower()
        # labels are normalized+lowercased; memorized methods should rank
        # their own (normalized) name highly
        hits += any(expected.startswith(n) or n == expected for n in names)
        assert m.n_contexts > 0 and m.n_oov == 0
        assert m.attention and m.attention[0][3] >= m.attention[-1][3]
        probs = [pr.prob for pr in m.predictions]
        assert probs == sorted(probs, reverse=True)
        assert 0 < sum(probs) <= 1.0 + 1e-6
    assert hits >= 4  # memorization: most train methods rank themselves


def test_oov_source_degrades_gracefully(trained):
    ds, out = trained
    p = Predictor(str(out), str(ds / "terminal_idxs.txt"), str(ds / "path_idxs.txt"))
    # try/catch + strings never occurred in training: most contexts OOV
    results = p.predict_source(
        "class X { String weird(String s) { try { return s.trim(); } "
        'catch (RuntimeException e) { return "x"; } } }',
        "weird", top_k=2,
    )
    assert len(results) == 1
    m = results[0]
    assert m.n_oov > 0
    assert len(m.predictions) == 2  # still returns ranked predictions


def test_task_mismatches_rejected(trained):
    ds, out = trained
    p = Predictor(str(out), str(ds / "terminal_idxs.txt"), str(ds / "path_idxs.txt"))
    # this checkpoint is method-task: variable prediction must refuse
    with pytest.raises(ValueError, match="not trained for the variable"):
        p.predict_variables(JAVA)
    # and a variable-only checkpoint must refuse method prediction
    meta_path = out / "model_meta.json"
    original = meta_path.read_text()
    meta = json.loads(original)
    meta["infer_method_name"] = False
    try:
        meta_path.write_text(json.dumps(meta))
        p2 = Predictor(str(out), str(ds / "terminal_idxs.txt"),
                       str(ds / "path_idxs.txt"))
        with pytest.raises(ValueError, match="variable-name task"):
            p2.predict_source(JAVA)
    finally:
        meta_path.write_text(original)


@pytest.fixture(scope="module")
def trained_vars(tmp_path_factory):
    """A variable-name-task model on the same extracted Java corpus."""
    return _train_on_util(
        tmp_path_factory, "predict_vars", infer_variable=True, epochs=30
    )


def test_predicts_memorized_variables(trained_vars):
    ds, out = trained_vars
    p = Predictor(str(out), str(ds / "terminal_idxs.txt"), str(ds / "path_idxs.txt"))
    results = p.predict_variables(JAVA, "*", top_k=3)
    # every method declares at least the parameters; JAVA has vars
    # total/product/even plus params a/b/n across 6 methods
    assert len(results) >= 12
    hits = 0
    for m in results:
        assert m.target_variable is not None
        assert m.n_contexts > 0
        names = [pr.name for pr in m.predictions]
        hits += m.target_variable.lower() in names
    assert hits >= len(results) // 2  # memorization ranks the true name


def test_missing_meta_explains(trained, tmp_path):
    ds, _ = trained
    with pytest.raises(FileNotFoundError, match="model_meta.json"):
        Predictor(str(tmp_path), str(ds / "terminal_idxs.txt"),
                  str(ds / "path_idxs.txt"))


PY = """
def add(a, b):
    total = a + b
    return total


def mul(a, b):
    product = a * b
    return product


def is_even(n):
    even = n % 2 == 0
    return even
"""


@pytest.fixture(scope="module")
def trained_py(tmp_path_factory):
    from code2vec_tpu.pyextract import extract_python_dataset

    root = tmp_path_factory.mktemp("predict_py")
    src = root / "src"
    ds = root / "ds"
    out = root / "out"
    for d in (src, ds, out):
        d.mkdir()
    (src / "util.py").write_text(PY)
    extract_python_dataset(str(ds), str(src), [("util.py", "*")])
    data = load_corpus(
        ds / "corpus.txt", ds / "path_idxs.txt", ds / "terminal_idxs.txt"
    )
    cfg = TrainConfig(
        max_epoch=25, batch_size=2, encode_size=32, terminal_embed_size=16,
        path_embed_size=16, max_path_length=64, lr=0.01,
        print_sample_cycle=0,
    )
    train(cfg, data, out_dir=str(out))
    return ds, out


def test_predicts_python_source(trained_py):
    ds, out = trained_py
    p = Predictor(str(out), str(ds / "terminal_idxs.txt"), str(ds / "path_idxs.txt"))
    results = p.predict_source(PY, "*", language="python", top_k=3)
    assert len(results) == 3
    for m in results:
        assert m.n_contexts > 0
        assert len(m.predictions) == 3


def test_rng_impl_round_trips(tmp_path):
    """A checkpoint trained with --rng_impl rbg must load for inference
    (meta carries the impl; the restore validates it)."""
    from code2vec_tpu.data.synth import SynthSpec, generate_corpus_files

    paths = generate_corpus_files(
        tmp_path / "ds",
        SynthSpec(n_methods=8, n_terminals=40, n_paths=30, n_labels=4,
                  mean_contexts=6.0, max_contexts=10, seed=3),
    )
    data = load_corpus(paths["corpus"], paths["path_idx"], paths["terminal_idx"])
    out = tmp_path / "out"
    out.mkdir()
    cfg = TrainConfig(max_epoch=1, batch_size=4, encode_size=16,
                      terminal_embed_size=8, path_embed_size=8,
                      max_path_length=8, rng_impl="rbg",
                      print_sample_cycle=0)
    train(cfg, data, out_dir=str(out))
    p = Predictor(str(out), paths["terminal_idx"], paths["path_idx"])
    assert p.meta["rng_impl"] == "rbg"


def test_extraction_params_follow_corpus(trained, tmp_path_factory):
    """predict must re-extract with the corpus's recorded caps, not the
    defaults — otherwise path strings silently diverge."""
    root = tmp_path_factory.mktemp("predict_caps")
    src = root / "src"
    ds = root / "ds"
    src.mkdir(), ds.mkdir()
    (src / "Util.java").write_text(JAVA)
    (ds / "methods.txt").write_text("Util.java\t*\n")
    extract_dataset(str(ds), str(src), max_length=12, max_width=4)
    _, out = trained  # any checkpoint; extraction params come from the ds
    p = Predictor(str(out), str(ds / "terminal_idxs.txt"), str(ds / "path_idxs.txt"))
    assert p.extract_params["max_length"] == 12
    assert p.extract_params["max_width"] == 4


def test_cli(trained, tmp_path, capsys):
    ds, out = trained
    f = tmp_path / "Util.java"
    f.write_text(JAVA)
    predict_main([
        str(f),
        "--model_path", str(out),
        "--terminal_idx_path", str(ds / "terminal_idxs.txt"),
        "--path_idx_path", str(ds / "path_idxs.txt"),
        "--method_name", "add",
        "--top_k", "2",
        "--show_attention", "1",
    ])
    printed = capsys.readouterr().out
    assert "add" in printed and "contexts" in printed
    assert "[" in printed  # an attention row


def test_cli_pins_cpu_by_default(trained, tmp_path, monkeypatch):
    """Inference must not touch the ambient device backend unless asked:
    JAX_PLATFORMS can point at a cold/wedged tunnel, and a one-off forward
    gains nothing from it (the examples/java demo hung exactly here)."""
    import code2vec_tpu.cli as cli_mod

    ds, out = trained
    f = tmp_path / "Util.java"
    f.write_text(JAVA)
    pins = []
    monkeypatch.setattr(cli_mod, "pin_platform", lambda no_cuda: pins.append(no_cuda))
    base = [
        str(f),
        "--model_path", str(out),
        "--terminal_idx_path", str(ds / "terminal_idxs.txt"),
        "--path_idx_path", str(ds / "path_idxs.txt"),
        "--method_name", "add",
        "--top_k", "1",
    ]
    predict_main(base)
    assert pins == [True]  # default: pin cpu
    predict_main(base + ["--accelerator"])
    assert pins[-1] is False  # explicit opt-in reaches the device backend
    predict_main(base + ["--accelerator", "--no_cuda"])
    assert pins[-1] is True  # an explicit --no_cuda always wins


def test_nearest_neighbors(trained, tmp_path, capsys):
    from code2vec_tpu.export import export_from_checkpoint
    from code2vec_tpu.predict import nearest_neighbors

    ds, out = trained
    vectors = tmp_path / "code.vec"
    cfg = TrainConfig(
        max_epoch=1, batch_size=4, encode_size=48, terminal_embed_size=24,
        path_embed_size=24, max_path_length=64, print_sample_cycle=0,
    )
    data = load_corpus(
        ds / "corpus.txt", ds / "path_idxs.txt", ds / "terminal_idxs.txt"
    )
    export_from_checkpoint(cfg, data, str(out), str(vectors))

    p = Predictor(str(out), str(ds / "terminal_idxs.txt"), str(ds / "path_idxs.txt"))
    (m,) = p.predict_source(JAVA, "add", top_k=1)
    assert m.code_vector is not None and m.code_vector.ndim == 1
    nn = nearest_neighbors(str(vectors), m.code_vector, top_k=3)
    assert len(nn) == 3
    # 'add' itself was exported; its own vector should rank at the top
    # with cosine ~1 (same model, same contexts up to per-epoch sampling)
    assert nn[0][1] > 0.9
    sims = [s for _, s in nn]
    assert sims == sorted(sims, reverse=True)

    # CLI path with explicit code.vec
    f = tmp_path / "Util.java"
    f.write_text(JAVA)
    predict_main([
        str(f),
        "--model_path", str(out),
        "--terminal_idx_path", str(ds / "terminal_idxs.txt"),
        "--path_idx_path", str(ds / "path_idxs.txt"),
        "--method_name", "add",
        "--neighbors", "2",
        "--code_vec_path", str(vectors),
    ])
    assert "~" in capsys.readouterr().out
