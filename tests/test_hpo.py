"""HPO engine tests: sampling distributions, median-pruner semantics
(optuna MedianPruner parity), and an end-to-end tiny search through the
real train loop (reference flow: main.py:429-488, 207-211)."""

import numpy as np
import pytest

from code2vec_tpu.hpo import (
    FrozenTrial,
    MedianPruner,
    Study,
    Trial,
    TrialPruned,
    find_optimal_hyperparams,
    sample_train_config,
)
from code2vec_tpu.data.reader import load_corpus
from code2vec_tpu.data.synth import SPECS, generate_corpus_files
from code2vec_tpu.train.config import TrainConfig


@pytest.fixture(scope="module")
def tiny_corpus(tmp_path_factory):
    out = tmp_path_factory.mktemp("tiny_hpo")
    paths = generate_corpus_files(out, SPECS["tiny"])
    return load_corpus(paths["corpus"], paths["path_idx"], paths["terminal_idx"])


def _trial(study: Study, seed: int = 0) -> Trial:
    record = FrozenTrial(number=len(study.trials), params={})
    study.trials.append(record)
    return Trial(study, record)


class TestSampling:
    def test_reference_search_space_ranges(self):
        study = Study(seed=7)
        base = TrainConfig()
        for seed in range(50):
            config = sample_train_config(_trial(study, seed), base)
            assert 100 <= config.encode_size <= 300
            assert 0.5 <= config.dropout_prob <= 0.9
            assert 256 <= config.batch_size <= 2048
            assert 1e-5 <= config.lr <= 1e-1
            assert 1e-10 <= config.weight_decay <= 1e-3

    def test_log_sampling_spans_orders_of_magnitude(self):
        study = Study()
        lrs = [
            _trial(study, s).suggest_float("lr", 1e-5, 1e-1, log=True)
            for s in range(200)
        ]
        # log-uniform: both the bottom and top decade should be populated
        assert any(lr < 1e-4 for lr in lrs)
        assert any(lr > 1e-2 for lr in lrs)

    def test_suggest_records_params(self):
        study = Study()
        trial = _trial(study)
        trial.suggest_int("encode_size", 100, 300, log=True)
        assert "encode_size" in trial.params


class TestMedianPruner:
    def _finished(self, number, values, state="complete"):
        return FrozenTrial(
            number=number,
            params={},
            intermediates=dict(enumerate(values)),
            value=values[-1],
            state=state,
        )

    def test_no_prune_during_startup_trials(self):
        study = Study(pruner=MedianPruner(n_startup_trials=5))
        for i in range(4):
            study.trials.append(self._finished(i, [0.1]))
        trial = _trial(study)
        trial.report(9.9, 0)
        assert not trial.should_prune()

    def test_prunes_below_median(self):
        study = Study(pruner=MedianPruner(n_startup_trials=2))
        for i, v in enumerate([0.1, 0.2, 0.3]):
            study.trials.append(self._finished(i, [v, v]))
        bad = _trial(study)
        bad.report(0.9, 0)
        assert bad.should_prune()
        good = _trial(study)
        good.report(0.05, 0)
        assert not good.should_prune()

    def test_uses_best_intermediate_so_far(self):
        # a trial that was once better than the median survives a bad epoch
        study = Study(pruner=MedianPruner(n_startup_trials=1))
        study.trials.append(self._finished(0, [0.5, 0.5]))
        trial = _trial(study)
        trial.report(0.1, 0)
        trial.report(0.9, 1)
        assert not trial.should_prune()

    def test_median_pool_uses_prior_trials_best_up_to_step(self):
        # a completed trial that regressed late ({0: 0.1, 1: 0.9})
        # contributes its best 0.1 to the median at step 1, so a 0.5 trial
        # is pruned (optuna semantics)
        study = Study(pruner=MedianPruner(n_startup_trials=1))
        study.trials.append(self._finished(0, [0.1, 0.9]))
        trial = _trial(study)
        trial.report(0.5, 1)
        assert trial.should_prune()

    def test_pruned_trials_excluded_from_median_pool(self):
        study = Study(pruner=MedianPruner(n_startup_trials=1))
        study.trials.append(self._finished(0, [0.2, 0.2]))
        study.trials.append(self._finished(1, [0.9, 0.9], state="pruned"))
        trial = _trial(study)
        trial.report(0.5, 1)  # above complete-median 0.2; pruned-0.9 ignored
        assert trial.should_prune()

    def test_warmup_steps_block_pruning(self):
        study = Study(pruner=MedianPruner(n_startup_trials=1, n_warmup_steps=3))
        study.trials.append(self._finished(0, [0.1, 0.1]))
        trial = _trial(study)
        trial.report(0.9, 1)
        assert not trial.should_prune()


class TestStudy:
    def test_optimize_tracks_best(self):
        study = Study(seed=3)
        values = iter([0.7, 0.2, 0.5])
        study.optimize(lambda t: next(values), n_trials=3)
        assert study.best_value == 0.2
        assert study.best_trial.number == 1

    def test_pruned_trials_are_recorded_not_best(self):
        study = Study(seed=3)

        def objective(trial):
            if trial.number == 0:
                trial.report(0.9, 0)
                raise TrialPruned
            return 0.4

        study.optimize(objective, n_trials=2)
        assert study.trials[0].state == "pruned"
        assert study.trials[0].value == pytest.approx(0.9)
        assert study.best_trial.number == 1


class TestTPESampler:
    """The reference's optuna default is TPE (main.py:460); the sampler
    must actually exploit structure, not just re-label random search."""

    @staticmethod
    def _bowl(trial):
        import math

        lr = trial.suggest_float("lr", 1e-5, 1e-1, log=True)
        drop = trial.suggest_float("drop", 0.0, 1.0)
        # smooth bowl: optimum at lr=1e-3, drop=0.3, min value 0
        return (math.log10(lr) + 3.0) ** 2 + 4 * (drop - 0.3) ** 2

    def _best(self, sampler, seed, n_trials=60):
        study = Study(seed=seed, sampler=sampler)
        study.optimize(self._bowl, n_trials=n_trials)
        return study.best_value

    def test_tpe_beats_random_on_synthetic_objective(self):
        seeds = range(5)
        tpe = [self._best("tpe", s) for s in seeds]
        rnd = [self._best("random", s) for s in seeds]
        # measured margins are ~8x (mean 0.004 vs 0.031 over seeds 0..7);
        # the assertions leave generous slack
        assert np.mean(tpe) < 0.5 * np.mean(rnd)
        assert np.mean(tpe) < 0.02

    def test_tpe_respects_bounds_and_int_domain(self):
        study = Study(seed=0, sampler="tpe")

        def objective(trial):
            size = trial.suggest_int("encode_size", 100, 300, log=True)
            assert isinstance(size, int) and 100 <= size <= 300
            return abs(size - 200) / 100.0

        study.optimize(objective, n_trials=30)
        assert all(100 <= t.params["encode_size"] <= 300 for t in study.trials)

    def test_tpe_concentrates_after_startup(self):
        study = Study(seed=1, sampler="tpe")
        study.optimize(self._bowl, n_trials=50)
        import math

        startup = [math.log10(t.params["lr"]) for t in study.trials[:10]]
        guided = [math.log10(t.params["lr"]) for t in study.trials[-20:]]
        # guided draws hug the optimum (-3) tighter than the startup draws
        assert np.mean(np.abs(np.array(guided) + 3.0)) < np.mean(
            np.abs(np.array(startup) + 3.0)
        )

    def test_pruned_trials_feed_observations(self):
        from code2vec_tpu.hpo import TPESampler, _Distribution

        study = Study(seed=0, sampler="tpe")

        def objective(trial):
            trial.suggest_float("x", 0.0, 1.0)
            if trial.number % 2 == 0:
                trial.report(0.5, 0)
                raise TrialPruned
            return 0.4

        study.optimize(objective, n_trials=12)
        sampler: TPESampler = study.sampler
        record = FrozenTrial(number=99, params={})
        obs = sampler._scored_observations(study, record, "x")
        assert len(obs) == 12  # pruned trials count by best intermediate


class TestEndToEnd:
    def test_tiny_search_runs_and_prunes_wire_up(self, tiny_corpus):
        # 2 trials x 2 epochs through the real jitted train loop; shrink the
        # space so shapes stay tiny (the sampler is exercised by TestSampling)
        base = TrainConfig(
            max_epoch=2,
            batch_size=16,
            max_path_length=16,
            terminal_embed_size=8,
            path_embed_size=8,
            print_sample_cycle=0,
            early_stop_patience=100,
        )
        import code2vec_tpu.hpo as hpo_mod

        original = hpo_mod.sample_train_config
        hpo_mod.sample_train_config = lambda trial, cfg: cfg.with_updates(
            encode_size=trial.suggest_int("encode_size", 8, 16, log=True),
            lr=trial.suggest_float("adam_lr", 1e-3, 1e-2, log=True),
        )
        try:
            study = find_optimal_hyperparams(
                tiny_corpus, base, n_trials=2, seed=0)
        finally:
            hpo_mod.sample_train_config = original
        assert len(study.trials) == 2
        assert all(t.state in ("complete", "pruned") for t in study.trials)
        best = study.best_trial
        assert 0.0 <= best.value <= 1.0
        assert best.intermediates  # per-epoch 1-f1 reports got recorded
