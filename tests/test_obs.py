"""The run-level telemetry subsystem (code2vec_tpu/obs/): the structured
event log (manifest completeness, strict-JSON hygiene, ordering under
threads), Chrome-trace span export, the runtime-health detectors, the
strided StepProfiler, and the end-to-end acceptance run: a CPU train with
an events dir + trace dir produces a manifest-first JSONL whose epoch
events match the sink-reported metrics exactly, and a Chrome trace
carrying spans from the prefetch producer thread, the train step, and
eval — with zero recompiles after warmup on the steady-shape path.
"""

import json
import math
import threading

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from code2vec_tpu.obs.events import (
    EventLog,
    metric_record,
    run_manifest,
    sanitize,
    sink_consumer,
)
from code2vec_tpu.obs.runtime import (
    RecompileDetector,
    RuntimeHealth,
    host_rss_bytes,
    memory_snapshot,
)
from code2vec_tpu.obs.trace import NullTracer, Tracer, get_tracer, set_tracer


def strict_loads(line: str):
    """json.loads that REJECTS the bare NaN/Infinity tokens json.dumps
    leaks by default — the property the sanitizers exist to guarantee."""
    def refuse(token):
        raise AssertionError(f"non-JSON constant {token!r} in output")

    return json.loads(line, parse_constant=refuse)


@pytest.fixture()
def installed_tracer():
    tracer = Tracer(process_index=0)
    previous = set_tracer(tracer)
    yield tracer
    set_tracer(previous)


class TestSanitize:
    def test_nonfinite_dict_values_null_with_raw(self):
        out = sanitize({"a": float("nan"), "b": float("inf"), "c": 1.5})
        assert out == {"a": None, "a_raw": "nan", "b": None, "b_raw": "inf", "c": 1.5}

    def test_numpy_scalars_unwrap(self):
        out = sanitize({"x": np.float32("nan"), "y": np.int64(3)})
        assert out["x"] is None and out["x_raw"] == "nan" and out["y"] == 3

    def test_unknown_objects_stringify(self):
        assert isinstance(sanitize({"d": object()})["d"], str)

    def test_metric_record_shapes(self):
        assert metric_record("f1", 0.5) == {"metric": "f1", "value": 0.5}
        assert metric_record("loss", float("nan")) == {
            "metric": "loss", "value": None, "raw": "nan",
        }
        assert metric_record("loss", float("-inf"))["raw"] == "-inf"


class TestMetricSinks:
    """Satellite regression: the line sinks must never print bare
    NaN/Infinity (invalid JSON) for non-finite metric values."""

    def test_floyd_sink_nonfinite_is_strict_json(self, capsys):
        from code2vec_tpu.sinks import floyd_sink

        floyd_sink(0, {"train_loss": float("nan"), "f1": 0.25})
        lines = [strict_loads(l) for l in capsys.readouterr().out.splitlines()]
        assert {"metric": "train_loss", "value": None, "raw": "nan"} in lines
        assert {"metric": "f1", "value": 0.25} in lines

    def test_logging_sink_nonfinite_is_strict_json(self, caplog):
        import logging

        from code2vec_tpu.sinks import logging_sink

        with caplog.at_level(logging.INFO, logger="code2vec_tpu.sinks"):
            logging_sink(1, {"test_loss": float("inf"), "f1": 1.0})
        payloads = [
            strict_loads(r.getMessage())
            for r in caplog.records
            if r.getMessage().startswith("{")
        ]
        assert {"metric": "test_loss", "value": None, "raw": "inf"} in payloads
        assert {"metric": "f1", "value": 1.0} in payloads

    def test_tensorboard_sink_has_close(self, tmp_path):
        pytest.importorskip("tensorboardX")
        from code2vec_tpu.sinks import tensorboard_sink

        sink = tensorboard_sink(str(tmp_path))
        sink(0, {"f1": 0.5})
        assert callable(sink.close)
        sink.close()


class TestEventLog:
    def test_manifest_is_first_line_and_complete(self, tmp_path):
        from code2vec_tpu.train.config import TrainConfig

        with EventLog(str(tmp_path)) as log:
            log.write_manifest(config=TrainConfig(batch_size=64))
            log.emit("epoch", epoch=0, metrics={"f1": 0.1})
        lines = [strict_loads(l) for l in open(log.path, encoding="utf-8")]
        manifest = lines[0]
        assert manifest["event"] == "manifest"
        for key in (
            "run_id", "config", "process_index", "process_count",
            "mesh_shape", "device_kind", "package_version",
        ):
            assert key in manifest, key
        assert manifest["config"]["batch_size"] == 64
        assert manifest["process_count"] == 1

    def test_manifest_idempotent(self, tmp_path):
        with EventLog(str(tmp_path)) as log:
            assert log.write_manifest() is not None
            assert log.write_manifest() is None

    def test_manifest_records_mesh_shape(self):
        from code2vec_tpu.parallel.mesh import make_mesh

        manifest = run_manifest(mesh=make_mesh(data=2, model=2))
        assert manifest["mesh_shape"] == {"data": 2, "model": 2, "ctx": 1}

    def test_event_ordering_under_threads(self, tmp_path):
        """Emitters on background threads (the prefetch producer pattern)
        must serialize: seq strictly increasing in file order, no
        interleaved/lost lines."""
        log = EventLog(str(tmp_path))
        n_threads, per_thread = 8, 50

        def emitter(tid):
            for i in range(per_thread):
                log.emit("step_sample", thread=tid, i=i)

        threads = [
            threading.Thread(target=emitter, args=(t,)) for t in range(n_threads)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        log.close()
        lines = [strict_loads(l) for l in open(log.path, encoding="utf-8")]
        assert len(lines) == n_threads * per_thread
        seqs = [l["seq"] for l in lines]
        assert seqs == sorted(seqs) and len(set(seqs)) == len(seqs)
        t_ms = [l["t_ms"] for l in lines]
        assert t_ms == sorted(t_ms)  # monotonic stamps in file order

    def test_consumers_get_raw_values_file_gets_sanitized(self, tmp_path):
        log = EventLog(str(tmp_path))
        seen = []
        log.subscribe(lambda e: seen.append(e))
        log.emit("epoch", epoch=0, metrics={"loss": float("nan")})
        log.close()
        assert math.isnan(seen[0]["metrics"]["loss"])  # raw to consumers
        line = strict_loads(open(log.path, encoding="utf-8").readline())
        assert line["metrics"]["loss"] is None
        assert line["metrics"]["loss_raw"] == "nan"

    def test_append_mode_preserves_previous_run(self, tmp_path):
        # a --resume'd run must extend the log (its manifest marks the
        # new segment), not truncate the recorded history
        with EventLog(str(tmp_path)) as log:
            log.write_manifest()
            log.emit("epoch", epoch=0, metrics={"f1": 0.1})
        with EventLog(str(tmp_path)) as resumed:
            resumed.write_manifest()
        lines = [strict_loads(l) for l in open(resumed.path, encoding="utf-8")]
        assert [l["event"] for l in lines] == ["manifest", "epoch", "manifest"]

    def test_construction_is_lazy_no_file_until_emit(self, tmp_path):
        # constructing must not open the file (nor resolve the process
        # index / touch the backend) — multi-host runs build the log
        # before jax.distributed.initialize
        log = EventLog(str(tmp_path), process_index=None)
        assert log.path is None and not list(tmp_path.iterdir())
        log.emit("epoch", epoch=0, metrics={})
        assert log.path is not None
        log.close()

    def test_run_id_pinned_by_env(self, monkeypatch):
        monkeypatch.setenv("C2V_RUN_ID", "pinned-run")
        assert run_manifest()["run_id"] == "pinned-run"

    def test_unsubscribe_stops_dispatch(self):
        log = EventLog()  # dispatch-only, no file
        seen = []
        consumer = log.subscribe(lambda e: seen.append(e))
        log.emit("epoch", epoch=0, metrics={})
        log.unsubscribe(consumer)
        log.emit("epoch", epoch=1, metrics={})
        assert len(seen) == 1

    def test_sink_consumer_routes_epoch_and_best_f1_only(self):
        calls = []
        consume = sink_consumer((lambda e, m: calls.append((e, m)),))
        consume({"event": "epoch", "epoch": 3, "metrics": {"f1": 0.5}})
        consume({"event": "best_f1", "epoch": 3, "metrics": {"best_f1": 0.5}})
        consume({"event": "checkpoint_saved", "epoch": 3})
        consume({"event": "eval", "epoch": 3, "metrics": {"f1": 0.5}})
        assert calls == [(3, {"f1": 0.5}), (3, {"best_f1": 0.5})]


class TestTracer:
    def test_chrome_trace_is_valid_and_complete(self, tmp_path):
        tracer = Tracer(process_index=2, process_name="host 2")
        with tracer.span("outer", category="test", epoch=0):
            with tracer.span("inner", step=1, queue_depth=2):
                pass
        done = threading.Event()

        def producer():
            with tracer.span("host_build", step=0):
                pass
            done.set()

        threading.Thread(target=producer, name="c2v-host-prefetch").start()
        assert done.wait(5.0)
        path = tracer.export_dir(str(tmp_path))
        trace = json.load(open(path, encoding="utf-8"))
        events = trace["traceEvents"]
        spans = [e for e in events if e.get("ph") == "X"]
        assert {s["name"] for s in spans} == {"outer", "inner", "host_build"}
        for s in spans:
            assert {"name", "cat", "ph", "ts", "dur", "pid", "tid"} <= set(s)
            assert s["pid"] == 2
        # per-process + per-thread track naming for multi-host merges
        meta = [e for e in events if e.get("ph") == "M"]
        assert any(
            m["name"] == "process_name" and m["args"]["name"] == "host 2"
            for m in meta
        )
        assert any(
            m["name"] == "thread_name" and m["args"]["name"] == "c2v-host-prefetch"
            for m in meta
        )
        # the producer span sits on its own thread track
        main_tid = next(s["tid"] for s in spans if s["name"] == "outer")
        prod_tid = next(s["tid"] for s in spans if s["name"] == "host_build")
        assert main_tid != prod_tid
        assert path.endswith("trace-p2.json")

    def test_span_args_and_nesting(self):
        tracer = Tracer()
        with tracer.span("outer"):
            with tracer.span("inner"):
                pass
        trace = tracer.chrome_trace()
        spans = {e["name"]: e for e in trace["traceEvents"] if e.get("ph") == "X"}
        # inner nests inside outer on the same track (duration containment;
        # ±2 µs slack for the epoch-anchored whole-µs ts rounding)
        assert spans["inner"]["ts"] >= spans["outer"]["ts"] - 2
        assert (
            spans["inner"]["ts"] + spans["inner"]["dur"]
            <= spans["outer"]["ts"] + spans["outer"]["dur"] + 2
        )
        # ts is anchored to the unix epoch so multi-host files merge on
        # one time axis
        import time as _time

        assert abs(spans["outer"]["ts"] / 1e6 - _time.time()) < 300

    def test_max_events_drop_is_counted(self):
        tracer = Tracer(max_events=2)
        for i in range(5):
            with tracer.span(f"s{i}"):
                pass
        trace = tracer.chrome_trace()
        assert len([e for e in trace["traceEvents"] if e.get("ph") == "X"]) == 2
        assert trace["dropped_events"] == 3

    def test_span_propagates_stop_iteration(self):
        # _SyncBatches wraps next() in a span; the epoch-ending
        # StopIteration must survive the context manager
        tracer = Tracer()
        it = iter([])
        with pytest.raises(StopIteration):
            with tracer.span("host_build"):
                next(it)
        assert [e["name"] for e in tracer.chrome_trace()["traceEvents"]
                if e.get("ph") == "X"] == ["host_build"]

    def test_reused_thread_idents_get_distinct_named_tracks(self):
        # CPython reuses thread idents once a thread dies (one producer
        # thread per epoch hits this constantly); two differently-named
        # occupants of one ident must land on distinct, correctly-named
        # trace rows
        tracer = Tracer(process_index=0)

        def spanner():
            with tracer.span("work"):
                pass

        for name in ("alpha", "beta"):
            t = threading.Thread(target=spanner, name=name)
            t.start()
            t.join()
        trace = tracer.chrome_trace()
        spans = [e for e in trace["traceEvents"] if e.get("ph") == "X"]
        assert len({s["tid"] for s in spans}) == 2
        labels = {
            m["tid"]: m["args"]["name"]
            for m in trace["traceEvents"]
            if m.get("name") == "thread_name"
        }
        assert set(labels.values()) >= {"alpha", "beta"}

    def test_null_tracer_default_and_set_restore(self):
        assert isinstance(get_tracer(), NullTracer)
        tracer = Tracer()
        previous = set_tracer(tracer)
        try:
            assert get_tracer() is tracer
        finally:
            set_tracer(previous)
        assert isinstance(get_tracer(), NullTracer)
        with get_tracer().span("ignored", step=1):
            pass  # inert and reusable


class TestRuntimeHealth:
    def test_counters_and_gauges_snapshot(self):
        health = RuntimeHealth()
        health.counter("recompiles").inc()
        health.counter("recompiles").inc(2)
        health.gauge("rss").set(123)
        snap = health.snapshot()
        assert snap["counters"]["recompiles"] == 3
        assert snap["gauges"]["rss"] == 123

    def test_host_rss_positive_on_linux(self):
        rss = host_rss_bytes()
        assert rss is not None and rss > 0

    def test_memory_snapshot_feeds_gauges(self):
        health = RuntimeHealth()
        snap = memory_snapshot(health)
        assert snap["host_rss_bytes"] > 0
        assert snap["host_peak_rss_bytes"] >= snap["host_rss_bytes"] // 2
        assert health.snapshot()["gauges"]["host_rss_bytes"] == snap["host_rss_bytes"]
        # CPU backend reports no device.memory_stats() — key absent, not null
        assert "device" not in snap or snap["device"] is not None


class TestRecompileDetector:
    def test_fires_on_shape_change_silent_on_steady_state(self):
        events = EventLog()
        seen = []
        events.subscribe(lambda e: seen.append(e))
        health = RuntimeHealth()
        detector = RecompileDetector(events=events, health=health)
        fn = jax.jit(lambda x: x + 1)
        detector.track("step", fn)

        fn(jnp.ones(4))
        assert detector.check(epoch=0) == 0  # warmup baseline
        fn(jnp.ones(4))
        fn(jnp.ones(4))
        assert detector.check(epoch=1) == 0  # steady shapes: silent
        assert detector.recompile_count == 0

        tracer = Tracer(process_index=0)
        previous = set_tracer(tracer)
        try:
            fn(jnp.ones(8))  # forced batch-shape churn
            assert detector.check(epoch=2) == 1
        finally:
            set_tracer(previous)
        # the recompile also lands as an instant mark on the trace timeline
        marks = [
            e for e in tracer.chrome_trace()["traceEvents"]
            if e.get("ph") == "i" and e["name"] == "recompile"
        ]
        assert len(marks) == 1 and marks[0]["args"]["fn"] == "step"
        assert detector.recompile_count == 1
        assert health.snapshot()["counters"]["recompiles"] == 1
        recompile = [e for e in seen if e["event"] == "recompile"]
        assert len(recompile) == 1
        assert recompile[0]["fn"] == "step" and recompile[0]["epoch"] == 2
        # and back to silence
        fn(jnp.ones(8))
        assert detector.check(epoch=3) == 0

    def test_non_jitted_functions_ignored(self):
        detector = RecompileDetector()
        detector.track("plain", lambda x: x)
        assert detector.check() == 0
        assert detector._tracked == {}

    def test_expected_compile_budget_stays_silent(self):
        """A budgeted multi-shape fn (bucketed batching: one compile per
        ladder width) stays silent up to its budget — even when the
        shapes arrive across several checks — and an over-budget compile
        still fires the recompile event."""
        events = EventLog()
        seen = []
        events.subscribe(lambda e: seen.append(e))
        detector = RecompileDetector(events=events)
        fn = jax.jit(lambda x: x * 2)
        detector.track("bucketed_step", fn, expected_compiles=3)

        fn(jnp.ones(4))
        fn(jnp.ones(8))
        assert detector.check(epoch=0) == 0  # 2 of 3 budgeted compiles
        fn(jnp.ones(16))  # the third ladder width, an epoch later
        assert detector.check(epoch=1) == 0  # still within budget
        assert detector.recompile_count == 0

        fn(jnp.ones(32))  # over budget: genuine shape churn
        assert detector.check(epoch=2) == 1
        assert detector.recompile_count == 1
        fired = [e for e in seen if e["event"] == "recompile"]
        assert len(fired) == 1
        assert fired[0]["fn"] == "bucketed_step" and fired[0]["epoch"] == 2
        # and silent again at the new steady state
        fn(jnp.ones(32))
        assert detector.check(epoch=3) == 0

    def test_expected_compile_budget_validated(self):
        detector = RecompileDetector()
        with pytest.raises(ValueError, match="expected_compiles"):
            detector.track("step", jax.jit(lambda x: x), expected_compiles=0)


class TestProducerSpanSampling:
    def test_span_steps_are_sampled_not_per_batch(self):
        from code2vec_tpu.train.prefetch import StepProfiler, _span_step

        # warmup + stride, never every step (16k-step epochs must not
        # flood the bounded trace buffer)
        spanned = [s for s in range(1000) if _span_step(s, None)]
        assert set(range(8)) <= set(spanned)
        assert 64 in spanned and 65 not in spanned
        assert len(spanned) < 40
        # profiler-fenced steps are always spanned
        prof = StepProfiler(sample_steps=1)
        prof.observe_epoch_length(1000)
        prof.reset()
        assert all(_span_step(s, prof) for s in range(1000) if prof.sampled(s))


class TestStridedProfiler:
    def test_first_epoch_is_first_n(self):
        from code2vec_tpu.train.prefetch import StepProfiler

        prof = StepProfiler(sample_steps=3)
        assert [s for s in range(10) if prof.sampled(s)] == [0, 1, 2]

    def test_stride_spreads_samples_across_epoch(self):
        from code2vec_tpu.train.prefetch import StepProfiler

        prof = StepProfiler(sample_steps=4)
        prof.observe_epoch_length(100)
        prof.reset()
        assert prof.stride == 25
        sampled = [s for s in range(100) if prof.sampled(s)]
        assert sampled == [0, 25, 50, 75]  # tail steps attributable too

    def test_sample_count_bounded_even_past_estimate(self):
        from code2vec_tpu.train.prefetch import StepProfiler

        prof = StepProfiler(sample_steps=4)
        prof.observe_epoch_length(100)
        prof.reset()
        # epoch ran longer than estimated: still at most sample_steps
        assert sum(prof.sampled(s) for s in range(1000)) == 4

    def test_summary_shape_unchanged(self):
        from code2vec_tpu.train.prefetch import StepProfiler

        prof = StepProfiler(sample_steps=2)
        prof.observe_epoch_length(8)
        prof.reset()
        for s in range(8):
            if prof.sampled(s):
                prof.record_host(s, 1.0, 2.0)
                prof.record_compute(s, 3.0)
        summary = prof.summary()
        assert set(summary) == {
            "host_build_ms", "h2d_ms", "feed_wait_ms", "compute_ms",
            "profiled_steps",
        }
        assert summary["profiled_steps"] == 2


@pytest.fixture(scope="module")
def tiny_corpus(tmp_path_factory):
    from code2vec_tpu.data.reader import load_corpus
    from code2vec_tpu.data.synth import SPECS, generate_corpus_files

    out = tmp_path_factory.mktemp("tiny_obs")
    paths = generate_corpus_files(out, SPECS["tiny"])
    return load_corpus(paths["corpus"], paths["path_idx"], paths["terminal_idx"])


class TestTrainTelemetryEndToEnd:
    """The acceptance criterion: a CPU train with events + tracing."""

    def test_train_run_events_and_trace(self, tiny_corpus, tmp_path):
        from code2vec_tpu.train.config import TrainConfig
        from code2vec_tpu.train.loop import train

        cfg = TrainConfig(
            max_epoch=2, batch_size=32, encode_size=32,
            terminal_embed_size=16, path_embed_size=16, max_path_length=16,
            print_sample_cycle=0, prefetch_batches=2, profile_steps=2,
            checkpoint_cycle=1,
        )
        events = EventLog(str(tmp_path / "events"))
        tracer = Tracer()
        previous = set_tracer(tracer)
        sink_calls = []

        class ClosableSink:
            closed = False

            def __call__(self, epoch, metrics):
                sink_calls.append((epoch, dict(metrics)))

            def close(self):
                self.closed = True

        sink = ClosableSink()
        (tmp_path / "ckpt").mkdir()
        try:
            train(
                cfg, tiny_corpus, out_dir=str(tmp_path / "ckpt"),
                sinks=(sink,), events=events, tracer=tracer,
            )
        finally:
            set_tracer(previous)
        events.close()
        trace_path = tracer.export_dir(str(tmp_path / "trace"))

        # (a) JSONL log: strict-JSON, manifest first, epoch events match
        # the sink-reported metrics exactly
        lines = [
            strict_loads(l)
            for l in open(events.path, encoding="utf-8")
        ]
        assert lines[0]["event"] == "manifest"
        assert lines[0]["config"]["batch_size"] == 32
        types = [l["event"] for l in lines]
        for expected in ("epoch", "step_sample", "eval", "checkpoint_saved"):
            assert expected in types, expected
        epoch_events = [l for l in lines if l["event"] == "epoch"]
        assert len(epoch_events) == 2
        for event in epoch_events:
            sink_metrics = next(
                m for e, m in sink_calls
                if e == event["epoch"] and "train_loss" in m
            )
            assert event["metrics"] == sink_metrics
            assert event["memory"]["host_rss_bytes"] > 0
            # the health block REPORTS the steady-shape recompile count
            assert event["health"]["counters"].get("recompiles", 0) == 0
            assert event["health"]["gauges"]["host_rss_bytes"] > 0
        # steady shapes: the recompile detector stayed silent after warmup
        assert not [l for l in lines if l["event"] == "recompile"]
        # the train loop's finally closed the closable sink
        assert sink.closed

        # (b) Chrome trace: loads, and carries spans from the prefetch
        # producer thread, the train step, and eval
        trace = json.load(open(trace_path, encoding="utf-8"))
        spans = [e for e in trace["traceEvents"] if e.get("ph") == "X"]
        names = {s["name"] for s in spans}
        assert {"host_build", "h2d", "train_step", "eval_pass"} <= names
        producer_tids = {s["tid"] for s in spans if s["name"] == "host_build"}
        step_tids = {s["tid"] for s in spans if s["name"] == "train_step"}
        assert producer_tids and step_tids and not (producer_tids & step_tids)
        # every span well-formed (B/E are unused; X events need ts + dur)
        for s in spans:
            assert s["dur"] >= 0 and s["ts"] >= 0

    def test_failures_clean_up_stream_consumer_and_sinks(self, tiny_corpus):
        """A raising run must emit an `error` event, unsubscribe the sink
        consumer from a caller-owned EventLog (no duplicate dispatch on
        the next train() over the same log), and close closable sinks; a
        SETUP failure (before any sink-visible event) must not leave a
        consumer attached either."""
        from code2vec_tpu.train.config import TrainConfig
        from code2vec_tpu.train.loop import train

        cfg = TrainConfig(
            max_epoch=1, batch_size=32, encode_size=16,
            terminal_embed_size=8, path_embed_size=8, max_path_length=8,
            print_sample_cycle=0,
        )
        closed = []
        def sink(epoch, metrics):
            pass
        sink.close = lambda: closed.append(True)

        # mid-loop failure: report_fn raises a non-StopTraining error
        events = EventLog()
        seen = []
        events.subscribe(lambda e: seen.append(e))
        def boom(epoch, f1):
            raise RuntimeError("boom")
        with pytest.raises(RuntimeError, match="boom"):
            train(cfg, tiny_corpus, sinks=(sink,), report_fn=boom, events=events)
        assert any(e["event"] == "error" for e in seen)
        assert len(events._consumers) == 1  # only this test's observer
        assert closed == [True]

        # setup failure: task-flag mismatch raises before any emission
        events2 = EventLog()
        bad = cfg.with_updates(infer_method_name=False, infer_variable_name=True)
        with pytest.raises(ValueError, match="task flags"):
            train(bad, tiny_corpus, events=events2)
        assert events2._consumers == []

    def test_passed_tracer_serves_whole_stack_without_global_install(
        self, tiny_corpus
    ):
        """train(tracer=...) without set_tracer must still capture the
        deeper layers' spans (they fetch the process-wide tracer): the
        loop installs the passed tracer for the run and restores after."""
        from code2vec_tpu.train.config import TrainConfig
        from code2vec_tpu.train.loop import train

        cfg = TrainConfig(
            max_epoch=1, batch_size=32, encode_size=16,
            terminal_embed_size=8, path_embed_size=8, max_path_length=8,
            print_sample_cycle=0, prefetch_batches=2,
        )
        tracer = Tracer(process_index=0)
        assert isinstance(get_tracer(), NullTracer)
        train(cfg, tiny_corpus, tracer=tracer)
        assert isinstance(get_tracer(), NullTracer)  # restored
        names = {
            e["name"]
            for e in tracer.chrome_trace()["traceEvents"]
            if e.get("ph") == "X"
        }
        assert {"host_build", "build_method_epoch", "train_pass"} <= names

    def test_hpo_search_shares_one_event_log(self, tiny_corpus, tmp_path):
        """--find_hyperparams --events_dir: every trial's events land in
        ONE log (regression: the HPO path used to drop the CLI's EventLog
        on the floor), with one manifest and no duplicate sink dispatch
        left behind by per-trial subscribe/unsubscribe."""
        import code2vec_tpu.hpo as hpo_mod
        from code2vec_tpu.train.config import TrainConfig

        base = TrainConfig(
            max_epoch=1, batch_size=16, max_path_length=16,
            terminal_embed_size=8, path_embed_size=8,
            print_sample_cycle=0, early_stop_patience=100,
        )
        original = hpo_mod.sample_train_config
        hpo_mod.sample_train_config = lambda trial, cfg: cfg.with_updates(
            lr=trial.suggest_float("adam_lr", 1e-3, 1e-2, log=True),
        )
        events = EventLog(str(tmp_path))
        try:
            hpo_mod.find_optimal_hyperparams(
                tiny_corpus, base, n_trials=2, seed=0, events=events
            )
        finally:
            hpo_mod.sample_train_config = original
        events.close()
        lines = [strict_loads(l) for l in open(events.path, encoding="utf-8")]
        types = [l["event"] for l in lines]
        assert types.count("manifest") == 1 and types[0] == "manifest"
        # the single manifest carries the BASE config, not trial 0's sample
        assert lines[0]["config"]["batch_size"] == 16
        assert lines[0]["search"]["n_trials"] == 2
        # trial markers segment the stream: trial → (its events) → result
        assert types.count("trial") == 2 and types.count("trial_result") == 2
        assert [l["number"] for l in lines if l["event"] == "trial"] == [0, 1]
        assert "adam_lr" in next(
            l for l in lines if l["event"] == "trial"
        )["params"]
        assert types.count("epoch") == 2  # one per trial (1 epoch each)
        assert events._consumers == []  # each trial unsubscribed its sinks

    def test_cli_flags_reach_telemetry(self):
        from code2vec_tpu.cli import build_parser

        args = build_parser().parse_args(
            ["--events_dir", "/tmp/e", "--trace_dir", "/tmp/t"]
        )
        assert args.events_dir == "/tmp/e" and args.trace_dir == "/tmp/t"
        assert build_parser().parse_args([]).events_dir is None

    def test_cli_end_to_end_writes_event_log_and_trace(self, tmp_path):
        from code2vec_tpu.cli import main

        out = tmp_path / "out"
        main([
            "--synthetic", "tiny",
            "--model_path", str(out),
            "--vectors_path", str(out / "code.vec"),
            "--max_epoch", "1",
            "--encode_size", "16",
            "--terminal_embed_size", "8",
            "--path_embed_size", "8",
            "--max_path_length", "8",
            "--print_sample_cycle", "0",
            "--events_dir", str(tmp_path / "events"),
            "--trace_dir", str(tmp_path / "trace"),
        ])
        lines = [
            strict_loads(l)
            for l in open(tmp_path / "events" / "events-p0.jsonl", encoding="utf-8")
        ]
        assert lines[0]["event"] == "manifest"
        assert any(l["event"] == "epoch" for l in lines)
        trace = json.load(
            open(tmp_path / "trace" / "trace-p0.json", encoding="utf-8")
        )
        names = {e.get("name") for e in trace["traceEvents"]}
        assert "train_step" in names and "eval_pass" in names
        # the CLI restores the process-wide tracer state is NOT required —
        # but a second run must not crash on a stale tracer
        assert json.dumps(trace)  # serializable round-trip
