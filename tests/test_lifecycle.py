"""Resource-lifecycle analyzer tests: the RS static rule family
(analysis/lifecycle.py) and the runtime handle ledger (obs/handles.py).

Static side: paired positive/negative fixtures per RS rule through
``lint_lifecycle`` (per-file pass + single-fragment finalize), the
cross-file RS005 finalize join, and ``# jaxlint: disable=`` suppression.

Runtime side: the off-by-default zero-cost contract (``track(x, k) is
x``, no attributes added, module state untouched — the plain-primitive
analogue of obs/sync.py's default contract), the debug-mode ledger
(gauges, snapshot, creation-site leak events, reported-once idempotence,
exclude), and open/close round trips through real owners (prefetcher,
micro-batcher, checkpoint writer).
"""

import textwrap

import numpy as np
import pytest

from code2vec_tpu.analysis.lifecycle import (
    check_source,
    finalize,
    lint_lifecycle,
)
from code2vec_tpu.obs import handles
from code2vec_tpu.obs.runtime import RuntimeHealth, global_health

pytestmark = pytest.mark.lifecycle


def _lint(source):
    return lint_lifecycle(textwrap.dedent(source))


def _rules(findings, *, include_suppressed=False):
    return sorted(
        f.rule
        for f in findings
        if include_suppressed or not f.suppressed
    )


# ---------------------------------------------------------------------------
# RS001 — unmanaged file/mmap/socket/SharedMemory
# ---------------------------------------------------------------------------


class TestRS001:
    def test_open_without_close_flagged(self):
        findings = _lint(
            """
            def read(p):
                f = open(p)
                data = f.read()
                return data
            """
        )
        assert _rules(findings) == ["RS001"]
        assert findings[0].snippet == "f = open(p)"

    def test_with_statement_clean(self):
        findings = _lint(
            """
            def read(p):
                with open(p) as f:
                    return f.read()
            """
        )
        assert _rules(findings) == []

    def test_try_finally_close_clean(self):
        findings = _lint(
            """
            def read(p):
                f = open(p)
                try:
                    return f.read()
                finally:
                    f.close()
            """
        )
        assert _rules(findings) == []

    def test_returned_handle_is_callers_problem(self):
        findings = _lint(
            """
            def make(p):
                f = open(p)
                return f
            """
        )
        assert _rules(findings) == []

    def test_handed_off_handle_not_flagged(self):
        # passing the bare name transfers ownership — over-approximate
        # toward silence
        findings = _lint(
            """
            def make(p, sink):
                f = open(p)
                sink(f)
            """
        )
        assert _rules(findings) == []

    def test_contextlib_closing_adopts(self):
        findings = _lint(
            """
            import contextlib
            import socket

            def probe(addr):
                s = socket.socket()
                with contextlib.closing(s):
                    s.connect(addr)
            """
        )
        assert _rules(findings) == []

    def test_socket_without_close_flagged(self):
        findings = _lint(
            """
            import socket

            def probe(addr):
                s = socket.socket()
                s.connect(addr)
            """
        )
        assert _rules(findings) == ["RS001"]


# ---------------------------------------------------------------------------
# RS002 — non-daemon thread with no join on the close path
# ---------------------------------------------------------------------------


class TestRS002:
    def test_started_thread_without_join_flagged(self):
        findings = _lint(
            """
            import threading

            class Worker:
                def __init__(self):
                    self._t = threading.Thread(target=self._run)
                    self._t.start()

                def _run(self):
                    pass

                def close(self):
                    pass
            """
        )
        assert "RS002" in _rules(findings)

    def test_join_reachable_from_close_clean(self):
        findings = _lint(
            """
            import threading

            class Worker:
                def __init__(self):
                    self._t = threading.Thread(target=self._run)
                    self._t.start()

                def _run(self):
                    pass

                def close(self):
                    self._stop()

                def _stop(self):
                    self._t.join()
            """
        )
        assert _rules(findings) == []

    def test_daemon_thread_exempt(self):
        findings = _lint(
            """
            import threading

            class Worker:
                def __init__(self):
                    self._t = threading.Thread(target=self._run, daemon=True)
                    self._t.start()

                def _run(self):
                    pass

                def close(self):
                    pass
            """
        )
        assert _rules(findings) == []

    def test_post_ctor_daemonization_exempt(self):
        findings = _lint(
            """
            import threading

            class Worker:
                def __init__(self):
                    self._t = threading.Thread(target=self._run)
                    self._t.daemon = True
                    self._t.start()

                def _run(self):
                    pass

                def close(self):
                    pass
            """
        )
        assert _rules(findings) == []


# ---------------------------------------------------------------------------
# RS003 — Popen without a reap on every exit path
# ---------------------------------------------------------------------------


class TestRS003:
    def test_popen_without_reap_flagged(self):
        findings = _lint(
            """
            import subprocess

            def run(cmd):
                proc = subprocess.Popen(cmd)
                print(proc.pid)
            """
        )
        assert _rules(findings) == ["RS003"]

    def test_popen_with_wait_clean(self):
        findings = _lint(
            """
            import subprocess

            def run(cmd):
                proc = subprocess.Popen(cmd)
                try:
                    print(proc.pid)
                finally:
                    proc.wait()
            """
        )
        assert _rules(findings) == []

    def test_popen_attr_without_reap_flagged(self):
        findings = _lint(
            """
            import subprocess

            class Replica:
                def __init__(self, cmd):
                    self._proc = subprocess.Popen(cmd)

                def close(self):
                    pass
            """
        )
        assert "RS003" in _rules(findings)

    def test_popen_attr_with_terminate_clean(self):
        findings = _lint(
            """
            import subprocess

            class Replica:
                def __init__(self, cmd):
                    self._proc = subprocess.Popen(cmd)

                def close(self):
                    self._proc.terminate()
                    self._proc.wait()
            """
        )
        assert _rules(findings) == []


# ---------------------------------------------------------------------------
# RS004 — temp dir/file without recorded cleanup
# ---------------------------------------------------------------------------


class TestRS004:
    def test_mkdtemp_without_cleanup_flagged(self):
        findings = _lint(
            """
            import tempfile

            def scratch():
                d = tempfile.mkdtemp()
                print(d)
            """
        )
        assert _rules(findings) == ["RS004"]

    def test_mkdtemp_with_atexit_register_clean(self):
        findings = _lint(
            """
            import atexit
            import shutil
            import tempfile

            def scratch():
                d = tempfile.mkdtemp()
                atexit.register(shutil.rmtree, d, ignore_errors=True)
                print(d)
            """
        )
        assert _rules(findings) == []

    def test_mkdtemp_with_rmtree_clean(self):
        findings = _lint(
            """
            import shutil
            import tempfile

            def scratch(fn):
                d = tempfile.mkdtemp()
                try:
                    fn(d)
                finally:
                    shutil.rmtree(d)
            """
        )
        assert _rules(findings) == []

    def test_returned_tempdir_is_callers_problem(self):
        findings = _lint(
            """
            import tempfile

            def scratch():
                d = tempfile.mkdtemp()
                return d
            """
        )
        assert _rules(findings) == []

    def test_delete_false_tempfile_without_cleanup_flagged(self):
        findings = _lint(
            """
            import tempfile

            def spill(data):
                tmp = tempfile.NamedTemporaryFile(delete=False)
                tmp.write(data)
                tmp.close()
                print(tmp.name)
            """
        )
        assert _rules(findings) == ["RS004"]

    def test_delete_true_tempfile_clean(self):
        findings = _lint(
            """
            import tempfile

            def spill(data):
                with tempfile.NamedTemporaryFile() as tmp:
                    tmp.write(data)
            """
        )
        assert _rules(findings) == []


# ---------------------------------------------------------------------------
# RS005 — resource-owning class without (complete) close
# ---------------------------------------------------------------------------


class TestRS005:
    def test_owner_without_close_flagged(self):
        findings = _lint(
            """
            class Holder:
                def __init__(self, p):
                    self.f = open(p)
            """
        )
        assert _rules(findings) == ["RS005"]
        assert "Holder" in findings[0].message

    def test_owner_with_close_clean(self):
        findings = _lint(
            """
            class Holder:
                def __init__(self, p):
                    self.f = open(p)

                def close(self):
                    self.f.close()
            """
        )
        assert _rules(findings) == []

    def test_close_missing_tracked_attr_flagged(self):
        findings = _lint(
            """
            class Holder:
                def __init__(self, p, q):
                    self.f = open(p)
                    self.g = open(q)

                def close(self):
                    self.f.close()
            """
        )
        assert _rules(findings) == ["RS005"]
        assert "g" in findings[0].message

    def test_exit_counts_as_close(self):
        findings = _lint(
            """
            class Holder:
                def __init__(self, p):
                    self.f = open(p)

                def __exit__(self, *exc):
                    self.f.close()
            """
        )
        assert _rules(findings) == []

    def test_cross_file_finalize_tracks_closeable_ctor(self):
        # a.py: Reader has close(); b.py: Owner stores a Reader in
        # __init__ but never closes it — only the repo-wide finalize
        # (joining both fragments) can see that Reader is closeable
        fa, frag_a = check_source(
            textwrap.dedent(
                """
                class Reader:
                    def __init__(self, p):
                        self.f = open(p)

                    def close(self):
                        self.f.close()
                """
            ),
            "a.py",
        )
        fb, frag_b = check_source(
            textwrap.dedent(
                """
                from a import Reader

                class Owner:
                    def __init__(self, p):
                        self.r = Reader(p)
                """
            ),
            "b.py",
        )
        assert _rules(fa) == [] and _rules(fb) == []
        joined = finalize([frag_a, frag_b])
        assert _rules(joined) == ["RS005"]
        assert joined[0].path == "b.py"

    def test_cross_file_close_closes_ctor_attr(self):
        _, frag_a = check_source(
            textwrap.dedent(
                """
                class Reader:
                    def __init__(self, p):
                        self.f = open(p)

                    def close(self):
                        self.f.close()
                """
            ),
            "a.py",
        )
        _, frag_b = check_source(
            textwrap.dedent(
                """
                from a import Reader

                class Owner:
                    def __init__(self, p):
                        self.r = Reader(p)

                    def close(self):
                        self.r.close()
                """
            ),
            "b.py",
        )
        assert _rules(finalize([frag_a, frag_b])) == []


# ---------------------------------------------------------------------------
# RS006 — executor/pool/queue without shutdown
# ---------------------------------------------------------------------------


class TestRS006:
    def test_executor_without_shutdown_flagged(self):
        findings = _lint(
            """
            from concurrent.futures import ThreadPoolExecutor

            class Pool:
                def __init__(self):
                    self._ex = ThreadPoolExecutor(max_workers=2)

                def close(self):
                    pass
            """
        )
        assert "RS006" in _rules(findings)

    def test_executor_with_shutdown_clean(self):
        findings = _lint(
            """
            from concurrent.futures import ThreadPoolExecutor

            class Pool:
                def __init__(self):
                    self._ex = ThreadPoolExecutor(max_workers=2)

                def close(self):
                    self._ex.shutdown(wait=True)
            """
        )
        assert _rules(findings) == []


# ---------------------------------------------------------------------------
# suppression / engine integration
# ---------------------------------------------------------------------------


class TestSuppression:
    def test_disable_comment_suppresses(self):
        findings = _lint(
            """
            def read(p):
                f = open(p)  # jaxlint: disable=RS001
                return f.read()
            """
        )
        assert _rules(findings) == []
        assert _rules(findings, include_suppressed=True) == ["RS001"]
        assert findings[0].suppressed

    def test_rules_registered_with_engine(self):
        from code2vec_tpu.analysis import jaxlint

        for rid in ("RS001", "RS002", "RS003", "RS004", "RS005", "RS006"):
            assert rid in jaxlint.RULES
            assert jaxlint.RULES[rid].severity == "warning"

    def test_syntax_error_is_silent(self):
        findings, fragment = check_source("def broken(:\n", "bad.py")
        assert findings == [] and not fragment.classes


# ---------------------------------------------------------------------------
# runtime ledger: off-by-default zero-cost contract
# ---------------------------------------------------------------------------


class _Probe:
    pass


class TestLedgerOff:
    def test_track_is_identity_and_stateless(self, monkeypatch):
        monkeypatch.delenv(handles.HANDLE_DEBUG_ENV, raising=False)
        handles.reset_handle_state()
        obj = _Probe()
        before = dict(vars(obj))
        assert handles.track(obj, "probe") is obj
        # bitwise-plain: no attributes added, no wrapper returned
        assert vars(obj) == before
        assert handles.untrack(obj) is False
        assert handles.open_handles() == []
        assert handles.handles_snapshot() == {"enabled": False}
        assert handles.report_leaks("off") == []

    def test_falsy_values_stay_off(self, monkeypatch):
        for value in ("", "0", "false", "no", "off", " OFF "):
            monkeypatch.setenv(handles.HANDLE_DEBUG_ENV, value)
            assert not handles.handle_debug_enabled()
        monkeypatch.setenv(handles.HANDLE_DEBUG_ENV, "1")
        assert handles.handle_debug_enabled()


# ---------------------------------------------------------------------------
# runtime ledger: debug mode
# ---------------------------------------------------------------------------


@pytest.fixture
def handle_debug(monkeypatch):
    monkeypatch.setenv(handles.HANDLE_DEBUG_ENV, "1")
    handles.reset_handle_state()
    yield
    handles.reset_handle_state()


class _Log:
    """EventLog stand-in collecting (kind, fields) pairs."""

    def __init__(self):
        self.events = []

    def emit(self, event, **fields):
        self.events.append((event, fields))


class TestLedgerOn:
    def test_track_untrack_round_trip(self, handle_debug):
        health = global_health()
        gauge = health.gauge("handles.open.probe")
        base = gauge.value or 0
        obj = _Probe()
        assert handles.track(obj, "probe", name="p0") is obj
        records = handles.open_handles("probe")
        assert [r["name"] for r in records] == ["p0"]
        # the creation site names THIS file — what the leak report prints
        assert "test_lifecycle" in records[0]["site"]
        assert gauge.value == base + 1
        snap = handles.handles_snapshot()
        assert snap["enabled"] and snap["open"]["probe"] == 1
        assert handles.untrack(obj) is True
        assert handles.untrack(obj) is False  # idempotent close paths
        assert gauge.value == base
        assert handles.open_handles("probe") == []

    def test_tokens_are_monotone(self, handle_debug):
        a, b = _Probe(), _Probe()
        handles.track(a, "probe")
        handles.track(b, "probe")
        tokens = [r["token"] for r in handles.open_handles()]
        assert tokens == sorted(tokens) and len(set(tokens)) == 2
        handles.untrack(a)
        handles.untrack(b)

    def test_report_leaks_emits_event_with_site(self, handle_debug):
        log = _Log()
        obj = _Probe()
        handles.track(obj, "probe", name="leaky")
        leaks = handles.report_leaks("test.shutdown", events=log)
        assert len(leaks) == 1
        assert [k for k, _ in log.events] == ["handle_leak"]
        _, fields = log.events[0]
        assert fields["where"] == "test.shutdown"
        assert fields["kind"] == "probe" and fields["name"] == "leaky"
        assert "test_lifecycle" in fields["site"]
        # the ledger is NOT cleared — post-report assertions still see it
        assert handles.open_handles("probe")
        assert handles.handles_snapshot()["leaked"] == 1

    def test_report_leaks_is_reported_once(self, handle_debug):
        log = _Log()
        handles.register_event_log(log)
        obj = _Probe()
        handles.track(obj, "probe")
        assert len(handles.report_leaks("first")) == 1
        # two teardown paths racing: the second report is silent
        assert handles.report_leaks("second") == []
        assert len(log.events) == 1

    def test_report_leaks_exclude(self, handle_debug):
        log = _Log()
        keep, leak = _Probe(), _Probe()
        handles.track(keep, "event_log")
        handles.track(leak, "probe")
        leaks = handles.report_leaks("x", events=log, exclude=(keep,))
        assert [r["kind"] for r in leaks] == ["probe"]

    def test_prefetcher_round_trip(self, handle_debug):
        from code2vec_tpu.train.prefetch import HostPrefetcher

        before = {r["token"] for r in handles.open_handles()}
        with HostPrefetcher(
            iter([{"x": np.zeros(2)}]), lambda b: b, depth=1
        ) as pf:
            assert handles.open_handles("prefetcher")
            list(pf)
        after = {r["token"] for r in handles.open_handles()}
        assert after <= before

    def test_batcher_round_trip(self, handle_debug):
        from code2vec_tpu.serve.batcher import MicroBatcher

        class _Engine:
            batch_sizes = (1, 4)
            max_width = 16

            def observe_width(self, width):
                pass

            def pad_requests(self, requests):
                batch = len(requests)
                width = max(len(r) for r in requests)
                zeros = np.zeros((batch, width), np.int32)
                return zeros, zeros, zeros, batch, width

            def run(self, starts, paths, ends):
                batch, width = starts.shape
                return (
                    np.zeros((batch, 4), np.float32),
                    np.ones((batch, 8), np.float32),
                    np.full((batch, width), 0.5, np.float32),
                )

        with MicroBatcher(
            _Engine(), deadline_ms=0.0, health=RuntimeHealth()
        ) as batcher:
            assert handles.open_handles("batcher")
            contexts = np.ones((3, 3), np.int32)
            batcher.submit(contexts).result(timeout=30)
        assert handles.open_handles("batcher") == []

    def test_checkpoint_writer_round_trip(self, handle_debug, tmp_path):
        from code2vec_tpu.checkpoint import CheckpointWriter

        writer = CheckpointWriter(str(tmp_path))
        assert handles.open_handles("checkpoint_writer")
        writer.close()
        assert handles.open_handles("checkpoint_writer") == []

    def test_event_log_round_trip(self, handle_debug, tmp_path):
        from code2vec_tpu.obs.events import EventLog

        log = EventLog(str(tmp_path))
        log.emit("x")  # lazy-open: tracking happens at first write
        assert handles.open_handles("event_log")
        log.close()
        assert handles.open_handles("event_log") == []

    def test_corpus_reader_round_trip(self, handle_debug, tmp_path):
        from code2vec_tpu.formats.corpus_io import (
            CorpusRecord,
            CsrCorpusWriter,
            open_corpus_csr,
        )

        path = str(tmp_path / "c.csr")
        with CsrCorpusWriter(path) as writer:
            writer.add(CorpusRecord(label="m", path_contexts=[(1, 2, 3)]))
        with open_corpus_csr(path) as corpus:
            assert handles.open_handles("mmap_corpus")
            assert corpus.n_items == 1
        assert handles.open_handles("mmap_corpus") == []
