"""Golden tests for the native extractor (SURVEY.md §4: 'small Java methods
-> exact expected path-context sets').

Expected paths are hand-derived from the extraction rules
(create_path_contexts.ipynb cells 6-10): anonymization, DFS terminal order,
common-prefix-strip paths with the width/length caps.
"""

import os

import pytest

from code2vec_tpu.extractor import (
    build_extractor,
    extract_dataset,
    extract_source,
)


@pytest.fixture(scope="session", autouse=True)
def built():
    build_extractor()


UP, DOWN = "↑", "↓"


def path_str(*parts):
    # helper: ("A","^"),("B","v"),... -> "A↑B↓..."
    out = []
    for name, arrow in parts[:-1]:
        out.append(name + (UP if arrow == "^" else DOWN))
    out.append(parts[-1][0])
    return "".join(out)


class TestMinimalMethod:
    SOURCE = "class A { int f(int a) { return a; } }"

    def test_exact_path_set(self):
        result = extract_source(self.SOURCE, "f")
        assert len(result.methods) == 1
        m = result.methods[0]
        assert m.label == "f"
        assert m.aliases == [("a", "@var_0")]

        terminals = result.terminal_vocab
        paths = result.path_vocab
        assert terminals == {1: "int", 2: "@method_0", 3: "@var_0"}

        # resolve features into (start_name, path_string, end_name)
        got = {
            (terminals[s], paths[p], terminals[e])
            for s, p, e in m.path_contexts
        }
        MD, PT, SN, PRM, BLK, RET, NE = (
            "MethodDeclaration",
            "PrimitiveType",
            "SimpleName",
            "Parameter",
            "BlockStmt",
            "ReturnStmt",
            "NameExpr",
        )
        expected = {
            ("int", f"{PT}{UP}{MD}{DOWN}{SN}", "@method_0"),
            ("int", f"{PT}{UP}{MD}{DOWN}{PRM}{DOWN}{PT}", "int"),
            ("int", f"{PT}{UP}{MD}{DOWN}{PRM}{DOWN}{SN}", "@var_0"),
            ("int", f"{PT}{UP}{MD}{DOWN}{BLK}{DOWN}{RET}{DOWN}{NE}{DOWN}{SN}", "@var_0"),
            ("@method_0", f"{SN}{UP}{MD}{DOWN}{PRM}{DOWN}{PT}", "int"),
            ("@method_0", f"{SN}{UP}{MD}{DOWN}{PRM}{DOWN}{SN}", "@var_0"),
            ("@method_0", f"{SN}{UP}{MD}{DOWN}{BLK}{DOWN}{RET}{DOWN}{NE}{DOWN}{SN}", "@var_0"),
            ("int", f"{PT}{UP}{PRM}{DOWN}{SN}", "@var_0"),
            ("int", f"{PT}{UP}{PRM}{UP}{MD}{DOWN}{BLK}{DOWN}{RET}{DOWN}{NE}{DOWN}{SN}", "@var_0"),
            ("@var_0", f"{SN}{UP}{PRM}{UP}{MD}{DOWN}{BLK}{DOWN}{RET}{DOWN}{NE}{DOWN}{SN}", "@var_0"),
        }
        assert got == expected


class TestAnonymization:
    def test_self_recursion_resolves_to_method_alias(self):
        result = extract_source(
            "class A { int f(int x) { return f(x + 1); } }", "f"
        )
        terminals = set(result.terminal_vocab.values())
        assert "@method_0" in terminals
        assert "f" not in terminals  # the name itself must not leak

    def test_external_call_keeps_name(self):
        result = extract_source(
            "class A { void f(B b) { b.run(); } }", "f"
        )
        assert "run" in set(result.terminal_vocab.values())

    def test_this_call_resolves_like_self(self):
        result = extract_source(
            "class A { int f() { return this.f(); } }", "f"
        )
        terminals = set(result.terminal_vocab.values())
        assert "@method_0" in terminals and "f" not in terminals

    def test_scoped_shadowing(self):
        # two independent blocks declare x -> two aliases; references
        # resolve to the innermost declaration
        src = """
        class A { void f() {
            { int x = 1; use(x); }
            { int x = 2; use(x); }
        } }
        """
        result = extract_source(src, "f")
        m = result.methods[0]
        # both declarations of x get distinct aliases (duplicate original
        # names are legitimate — dict() would collapse them)
        assert {alias for _, alias in m.aliases} >= {"@var_0", "@var_1"}
        assert [orig for orig, _ in m.aliases] == ["x", "x"]

    def test_label_resolution(self):
        src = "class A { void f() { foo: while (true) { break foo; } } }"
        result = extract_source(src, "f")
        terminals = set(result.terminal_vocab.values())
        assert "@label_0" in terminals
        assert "foo" not in terminals
        assert ("foo", "@label_0") in result.methods[0].aliases

    def test_variable_reference_uses_declaration_alias(self):
        src = "class A { void f(int count) { int total = count; } }"
        result = extract_source(src, "f")
        m = result.methods[0]
        assert dict(m.aliases) == {"count": "@var_0", "total": "@var_1"}


class TestLiteralNormalization:
    SRC = 'class A { void f() { g("s", \'c\', 7, 3.5); } }'

    def test_defaults(self):
        terminals = set(extract_source(self.SRC, "f").terminal_vocab.values())
        assert "@string_literal" in terminals
        assert "@char_literal" in terminals
        assert "@double_literal" in terminals
        assert "7" in terminals  # ints NOT normalized by default (cell12)

    def test_int_normalization_flag(self):
        terminals = set(
            extract_source(self.SRC, "f", normalize_int=True).terminal_vocab.values()
        )
        assert "@int_literal" in terminals and "7" not in terminals

    def test_terminals_lowercased(self):
        result = extract_source("class A { void f(Foo myVar) { } }", "f")
        names = set(result.terminal_vocab.values())
        assert "foo" in names  # type name lowercased (cell7)


class TestIgnorableMethods:
    @pytest.mark.parametrize(
        "src",
        [
            "class A { public String getHashKey(); }",  # abstract
            "class A { public String toString() { return \"x\"; } }",
            "class A { void setX(int x) { this.x = x; } }",  # trivial setter
            "class A { int getX() { return x; } }",  # trivial getter
            "class A { boolean isOk() { return ok; } }",
        ],
    )
    def test_skipped(self, src):
        assert extract_source(src, "*").methods == []

    @pytest.mark.parametrize(
        "src,name",
        [
            # setter with 2 params is NOT trivial
            ("class A { void setX(int x, int y) { this.x = x; } }", "setX"),
            # getter with a param is NOT trivial
            ("class A { int getX(int i) { return a[i]; } }", "getX"),
            # get* with 2 statements is NOT trivial
            ("class A { int getY() { int z = 1; return z; } }", "getY"),
        ],
    )
    def test_kept(self, src, name):
        assert [m.label for m in extract_source(src, "*").methods] == [name]


class TestPathCaps:
    def test_width_cap(self):
        # call with 5 args: first and last arg diverge at sibling distance 5
        src = "class A { void f() { g(a, b, c, d, e); } }"
        wide = extract_source(src, "f", max_width=10)
        narrow = extract_source(src, "f", max_width=1)
        assert len(wide.methods[0].path_contexts) > len(
            narrow.methods[0].path_contexts
        )

    def test_length_cap(self):
        src = "class A { int f(int a) { return ((((a)))); } }"
        long_ok = extract_source(src, "f", max_length=20)
        short = extract_source(src, "f", max_length=4)
        assert len(long_ok.methods[0].path_contexts) > len(
            short.methods[0].path_contexts
        )

    def test_caps_match_reference_defaults(self):
        # defaults 8/3 (top11_dataset/params.txt:1-2)
        result = extract_source("class A { int f(int a) { return a; } }", "f")
        assert len(result.methods[0].path_contexts) == 10


class TestOperatorsAndStructures:
    def test_operator_suffixed_nodes(self):
        result = extract_source(
            "class A { int f(int a, int b) { a += b * 2; return -a; } }", "f"
        )
        paths = " ".join(result.path_vocab.values())
        assert "AssignExpr:PLUS" in paths
        assert "BinaryExpr:MULTIPLY" in paths
        assert "UnaryExpr:MINUS" in paths

    def test_conditional_wrapper(self):
        result = extract_source(
            "class A { int f(int a) { return a > 0 ? a : 0; } }", "f"
        )
        assert "Condition" in " ".join(result.path_vocab.values())

    def test_lambda_and_generics(self):
        src = """
        class A {
            java.util.List<String> f(java.util.Map<String, java.util.List<Integer>> m) {
                return m.keys().stream().map(k -> k.trim()).collect();
            }
        }
        """
        result = extract_source(src, "f")
        assert result.methods and result.methods[0].path_contexts

    def test_try_catch_foreach_switch(self):
        src = """
        class A {
            int f(int[] xs) {
                int total = 0;
                for (int x : xs) {
                    try { total += x; } catch (RuntimeException | Error e) { throw e; }
                }
                switch (total) { case 0: return 1; default: break; }
                do { total--; } while (total > 10);
                return total;
            }
        }
        """
        result = extract_source(src, "f")
        assert len(result.methods[0].path_contexts) > 20

    def test_anonymous_class_and_arrays(self):
        src = """
        class A {
            Object f() {
                int[][] grid = new int[3][];
                String[] names = new String[] { "x", "y" };
                return new Runnable() { public void go() { } };
            }
        }
        """
        result = extract_source(src, "f")
        assert result.methods[0].path_contexts

    def test_parse_error_raises(self):
        with pytest.raises(ValueError, match="extraction failed"):
            extract_source("class A { int f( { }", "f")

    @pytest.mark.parametrize(
        "src",
        [
            # regressions from review: constructs that used to drop files
            "class A { String f() { return (String) null; } }",
            "class A { boolean f() { return (Boolean) true; } }",
            'class A { void f() { @SuppressWarnings("x") int y = 1; g(y); } }',
            "class A { void f() { java.util.Collections.<String>emptyList(); } }",
            "class A { void f() { Foo.<java.util.List<String>>of(); } }",
            "class A { void f() { final class B { void g() { } } } }",
        ],
    )
    def test_review_regressions_parse(self, src):
        result = extract_source(src, "f")
        assert len(result.methods) == 1


class TestDatasetCLI:
    def test_end_to_end_to_training(self, tmp_path):
        """Java sources -> extractor CLI -> load_corpus -> a training epoch:
        the full pipeline the reference implements in two disconnected
        halves, end to end."""
        src_dir = tmp_path / "src"
        ds_dir = tmp_path / "ds"
        os.makedirs(src_dir)
        os.makedirs(ds_dir)
        for i in range(6):
            (src_dir / f"C{i}.java").write_text(
                f"""
                class C{i} {{
                    int computeTotal(int[] values) {{
                        int total = 0;
                        for (int v : values) {{ total += v + {i}; }}
                        return total;
                    }}
                    String formatName(String first, String last) {{
                        return first + " " + last + {i};
                    }}
                }}
                """
            )
        rows = []
        for i in range(6):
            rows.append(f"C{i}.java\tcomputeTotal")
            rows.append(f"C{i}.java\tformatName")
        (ds_dir / "methods.txt").write_text("\n".join(rows) + "\n")

        result = extract_dataset(str(ds_dir), str(src_dir),
                                 method_declarations="method_declarations.txt")
        assert "extracted 12 methods" in result.stderr

        from code2vec_tpu.data.reader import load_corpus
        from code2vec_tpu.formats import read_params
        from code2vec_tpu.train.config import TrainConfig
        from code2vec_tpu.train.loop import train

        params = read_params(ds_dir / "params.txt")
        assert params["method_count"] == "12"
        assert params["max_length"] == "8"

        data = load_corpus(
            ds_dir / "corpus.txt",
            ds_dir / "path_idxs.txt",
            ds_dir / "terminal_idxs.txt",
        )
        assert data.n_items == 12
        assert data.method_token_index is not None

        cfg = TrainConfig(
            max_epoch=1,
            batch_size=8,
            encode_size=16,
            terminal_embed_size=8,
            path_embed_size=8,
            max_path_length=32,
            print_sample_cycle=0,
        )
        res = train(cfg, data)
        assert res.epochs_run == 1

        # auxiliary artifacts
        assert (ds_dir / "actual_methods.txt").read_text().count("\n") == 12
        decls = (ds_dir / "method_declarations.txt").read_text()
        assert "computeTotal" in decls


class TestConstructorChainingAndMethodRefs:
    """Regression: these constructs previously failed the whole file
    (parser.cc parse_statement / parse_postfix)."""

    def test_super_invocation_with_args(self):
        src = "class B extends A { B(int x) { super(x); } void f() { g(); } }"
        assert [m.label for m in extract_source(src, "f").methods] == ["f"]

    def test_zero_arg_super_and_this_chain(self):
        src = "class C { C() { this(1); } C(int x) { super(); } void f() { h(); } }"
        assert [m.label for m in extract_source(src, "f").methods] == ["f"]

    def test_constructor_reference(self):
        result = extract_source("class A { void f() { g(Runnable::new); } }", "f")
        assert any(
            "MethodReferenceExpr" in p for p in result.path_vocab.values()
        )

    def test_array_constructor_reference(self):
        result = extract_source("class A { void f() { g(String[]::new); } }", "f")
        assert any(
            "MethodReferenceExpr↓ArrayType" in p
            for p in result.path_vocab.values()
        )


HARD_CASES = {
    "generic_method_call": "class A { void f() { java.util.Collections.<String>emptyList(); } }",
    "nested_generics": "class A { java.util.Map<String, java.util.List<int[]>> m; void f() { m = new java.util.HashMap<>(); } }",
    "shift_vs_generics": "class A { int f(int x) { java.util.Map<String, java.util.List<String>> m = new java.util.HashMap<>(); int y = x >> 2; return m.size() + (y >>> 1); } }",
    "relational_ops": "class A { boolean f(int a, int b) { return a < b && b > 3; } }",
    "ternary_nest": "class A { int f(int x) { return x > 0 ? x < 10 ? 1 : 2 : 0; } }",
    "anon_class": "class A { Runnable f() { return new Runnable() { public void run() { int x = 1; } }; } }",
    "static_nested_enum": "class A { enum E { X, Y { void g() {} }; void g() {} } int f() { return E.X.ordinal(); } }",
    "varargs": "class A { int f(int... xs) { int s = 0; for (int x : xs) s += x; return s; } }",
    "try_with_resources": "class A { void f() { try (java.io.StringReader r = new java.io.StringReader(\"x\"); java.io.StringReader q = new java.io.StringReader(\"y\")) { r.read(); } catch (Exception e) { } finally { } } }",
    "multi_catch": "class A { void f() { try { g(); } catch (IllegalStateException | IllegalArgumentException e) { throw e; } } void g() {} }",
    "labeled_loops": "class A { void f() { outer: for (int i = 0; i < 3; i++) { for (int j = 0; j < 3; j++) { if (j > i) continue outer; if (i == 2) break outer; } } } }",
    "lambda_block": "class A { java.util.function.Function<Integer,Integer> f() { return x -> { int y = x + 1; return y * 2; }; } }",
    "method_ref_static": "class A { java.util.function.Function<String,Integer> f() { return Integer::parseInt; } }",
    "array_of_arrays": "class A { int f() { int[][] g = new int[2][3]; g[0][1] = 5; return g[0][1]; } }",
    "array_init": "class A { int[] f() { return new int[]{1, 2, 3}; } }",
    "cast_chain": "class A { long f(Object o) { return ((Number) o).longValue(); } }",
    "instanceof_": "class A { boolean f(Object o) { return o instanceof String; } }",
    "switch_fallthrough": "class A { int f(int x) { switch (x) { case 1: case 2: return 1; default: return 0; } } }",
    "synchronized_": "class A { void f() { synchronized (this) { int x = 1; } } }",
    "inner_class_access": "class A { class B { int y; } int f() { B b = new B(); return b.y; } }",
    "interface_default": "interface I { default int f(int x) { return x + 1; } static int g() { return 2; } }",
    "annotations": "class A { @Deprecated @SuppressWarnings({\"unchecked\", \"raw\"}) int f() { return 1; } }",
    "char_ops": "class A { boolean f(char c) { return c >= 'a' && c <= 'z'; } }",
    "bit_ops": "class A { int f(int x) { return (x << 2) | (x >>> 1) ^ (x >> 3) & ~x; } }",
    "hex_bin_literals": "class A { long f() { return 0xFFL + 0b1010 + 017 + 1_000_000 + 1e-3 > 0 ? 1L : 0L; } }",
    "generic_bounds": "class A { <T extends Comparable<? super T>> T max(java.util.List<? extends T> xs) { T best = xs.get(0); for (T x : xs) if (x.compareTo(best) > 0) best = x; return best; } }",
    "this_chain": "class A { int v; A set(int v) { this.v = v; return this; } int f() { return set(3).v; } }",
    "super_call": "class B { int g() { return 1; } } class A extends B { int g() { return super.g() + 1; } }",
    "static_init_field": "class A { static int X; static { X = 3; } int f() { return X; } }",
    "do_while": "class A { int f(int x) { int n = 0; do { n++; x /= 2; } while (x > 0); return n; } }",
    "assert_stmt": "class A { void f(int x) { assert x > 0 : \"bad\" + x; } }",
    "constructor_this": "class A { int v; A() { this(5); } A(int v) { this.v = v; } int f() { return v; } }",
    "unicode_ident": "class A { int f() { int café = 2; return café; } }",
}


class TestHardJavaConstructs:
    """Parse-robustness corpus: every construct must parse and yield at
    least one path-context (regression net for the hand-written parser)."""

    @pytest.mark.parametrize("name", sorted(HARD_CASES))
    def test_parses_and_extracts(self, name):
        result = extract_source(HARD_CASES[name])
        assert result.methods, f"{name}: no methods extracted"
        # per-method, not aggregate: a regression that drops one method's
        # body (the construct under test) must not be masked by siblings
        for m in result.methods:
            assert m.path_contexts, f"{name}: method {m.label!r} empty"


class TestModernJava:
    """Exact-semantics goldens for post-javaparser-3.6 constructs (Java
    10-21): var, records/compact constructors, switch expressions with
    arrow entries + yield + (guarded) type patterns, instanceof patterns,
    text blocks. The reference cannot parse any of these (its javaparser
    is 3.6.17); semantics here extend ipynb cell6's rules: VarType is a
    leaf type terminal, PatternExpr anonymizes its binding like a
    declarator, record bodies close scope like class bodies."""

    def test_var_paths(self):
        r = extract_source("class A { int f(int a) { var b = a; return b; } }", "f")
        m = r.methods[0]
        assert sorted(m.aliases) == [("a", "@var_0"), ("b", "@var_1")]
        assert set(r.terminal_vocab.values()) == {
            "int", "@method_0", "@var_0", "@var_1", "var"}
        got = {(r.terminal_vocab[s], r.path_vocab[p], r.terminal_vocab[e])
               for s, p, e in m.path_contexts}
        # declarator name <-> inferred type; initializer resolves to @var_0
        assert ("@var_1", f"SimpleName{UP}VariableDeclarator{DOWN}VarType", "var") in got
        assert ("var", f"VarType{UP}VariableDeclarator{DOWN}NameExpr{DOWN}SimpleName", "@var_0") in got

    def test_switch_expression_shape(self):
        r = extract_source(
            "class A { int f(int d) { return switch (d) "
            "{ case 1 -> 10; default -> 0; }; } }", "f")
        m = r.methods[0]
        got = {(r.terminal_vocab[s], r.path_vocab[p], r.terminal_vocab[e])
               for s, p, e in m.path_contexts}
        # selector and an arrow-entry body hang off SwitchExpr under ReturnStmt,
        # entry node keeps the 3.6 name SwitchEntryStmt
        assert ("@var_0",
                f"SimpleName{UP}NameExpr{UP}SwitchExpr{DOWN}SwitchEntryStmt{DOWN}IntegerLiteralExpr",
                "1") in got

    def test_yield_statement(self):
        r = extract_source(
            "class A { int f(int d) { return switch (d) "
            "{ default: yield d + 1; }; } }", "f")
        m = r.methods[0]
        got = {(r.terminal_vocab[s], r.path_vocab[p], r.terminal_vocab[e])
               for s, p, e in m.path_contexts}
        assert ("@var_0",
                f"SimpleName{UP}NameExpr{UP}BinaryExpr:PLUS{DOWN}IntegerLiteralExpr",
                "1") in got
        assert any("YieldStmt" in r.path_vocab[p] for _, p, _ in m.path_contexts)

    def test_instanceof_pattern_binding_resolves(self):
        r = extract_source(
            "class A { int f(Object o) { if (o instanceof Integer n && n > 0) "
            "return n; return 0; } }", "f")
        m = r.methods[0]
        assert ("n", "@var_1") in m.aliases
        got = {(r.terminal_vocab[s], r.path_vocab[p], r.terminal_vocab[e])
               for s, p, e in m.path_contexts}
        # the guard's right operand sees the binding introduced on the left
        assert ("@var_1",
                f"SimpleName{UP}PatternExpr{UP}InstanceOfExpr{UP}BinaryExpr:AND{DOWN}BinaryExpr:GREATER{DOWN}NameExpr{DOWN}SimpleName",
                "@var_1") in got

    def test_record_component_and_method(self):
        r = extract_source(
            "record Point(int x, int y) { int dist(Point o) "
            "{ return x * o.x; } }", "dist")
        m = r.methods[0]
        # o is the method's own parameter; record components x/y sit outside
        # the method subtree and are untouched (field-reference semantics)
        assert m.aliases == [("o", "@var_0")]
        used = {r.terminal_vocab[i] for s, _, e in m.path_contexts for i in (s, e)}
        assert "x" in used and "@var_0" in used

    def test_compact_constructor_not_a_method(self):
        r = extract_source(
            "record R(int x) { R { x = Math.abs(x); } int f() { return x; } }",
            "*")
        assert [m.label for m in r.methods] == ["f"]

    def test_text_block_normalizes_to_string_literal(self):
        r = extract_source(
            'class A { String f(String p) { return p + """\n  a "b"\n  c"""; } }',
            "f")
        m = r.methods[0]
        used = {r.terminal_vocab[i] for s, _, e in m.path_contexts for i in (s, e)}
        assert "@string_literal" in used

    def test_sealed_and_permits_stripped(self):
        r = extract_source(
            "sealed class A permits B { int f(int v) { return v; } } "
            "final class B extends A { }", "f")
        assert [m.label for m in r.methods] == ["f"]
        assert "sealed" not in set(r.terminal_vocab.values())


class TestParallelExtraction:
    """--jobs N must produce byte-identical artifacts and identical
    per-row stderr diagnostics to the sequential pipeline: workers extract
    to strings, the committer interns in row order (main.cc)."""

    ARTIFACTS = ("corpus.txt", "terminal_idxs.txt", "path_idxs.txt",
                 "params.txt", "actual_methods.txt", "decls.txt")

    def _make_dataset(self, root):
        src = root / "src"
        src.mkdir()
        # enough distinct files that groups actually interleave across
        # workers, plus every error shape the sequential loop reports
        for i in range(12):
            (src / f"F{i}.java").write_text(
                f"class F{i} {{\n"
                f"  int alpha{i}(int a, int b) {{ return a * b + {i}; }}\n"
                f"  void beta{i}(String s) {{ System.out.println(s + alpha{i}(1, 2)); }}\n"
                f"}}\n"
            )
        (src / "Broken.java").write_text("class Broken { int f( { }")
        rows = []
        for i in range(12):
            rows.append(f"F{i}.java\talpha{i}")
            rows.append(f"F{i}.java\t*")  # consecutive same-file rows
        rows.insert(5, "Broken.java\t*")        # parse error mid-stream
        rows.insert(9, "Missing.java\tf")       # unreadable file
        rows.insert(13, "F0.java\tnoSuchMethod")  # method-not-found warning
        dataset = root / "ds"
        dataset.mkdir()
        (dataset / "methods.txt").write_text("\n".join(rows) + "\n")
        return dataset, src

    def _run(self, tmp_path, name, jobs):
        root = tmp_path / name
        root.mkdir()
        dataset, src = self._make_dataset(root)
        result = extract_dataset(
            str(dataset), str(src), method_declarations="decls.txt",
            extra_args=["--jobs", str(jobs)],
        )
        blobs = {
            a: (dataset / a).read_bytes() for a in self.ARTIFACTS
        }
        # the "cannot open <abs path>" diagnostic embeds the per-run tmp dir
        return blobs, result.stderr.replace(str(src), "<src>")

    def test_group_row_cap_splits_long_same_file_runs(self, tmp_path):
        """A same-file run longer than GroupReader::kMaxRowsPerGroup (4096)
        is split into sub-groups — memory stays bounded — and the split is
        invisible in the artifacts: sub-groups re-parse the same CU and the
        committer preserves row order (main.cc)."""

        def run(name, jobs):
            root = tmp_path / name
            root.mkdir()
            src = root / "src"
            src.mkdir()
            (src / "Gen.java").write_text(
                "class Gen {\n"
                "  int pick(int a) { return a + 1; }\n"
                "  void emit(String s) { System.out.println(s); }\n"
                "}\n"
            )
            # one run of 4100 consecutive same-file rows (> the 4096 cap),
            # alternating named-method and method-not-found rows so commit
            # order is observable in both corpus.txt and stderr
            rows = []
            for i in range(4100):
                rows.append("Gen.java\tpick" if i % 2 == 0
                            else f"Gen.java\tmissing{i}")
            dataset = root / "ds"
            dataset.mkdir()
            (dataset / "methods.txt").write_text("\n".join(rows) + "\n")
            result = extract_dataset(
                str(dataset), str(src), method_declarations="decls.txt",
                extra_args=["--jobs", str(jobs)],
            )
            blobs = {a: (dataset / a).read_bytes() for a in self.ARTIFACTS}
            return blobs, result.stderr

        seq_blobs, seq_err = run("seq", jobs=1)
        par_blobs, par_err = run("par", jobs=4)
        # decls.txt included: it dumps per-row method SOURCE, the artifact
        # most exposed to the sub-group re-parse, so the split's
        # invisibility must hold for it byte-for-byte too
        assert par_blobs == seq_blobs
        assert par_err == seq_err
        # every named row extracted, every missingN row warned, in order
        assert seq_blobs["corpus.txt"].count(b"label:pick") == 2050
        # one "#<id>\t<file>#<name>" decl header per extracted row
        assert seq_blobs["decls.txt"].count(b"Gen.java#pick\n") == 2050
        assert seq_err.count("WARNING: method not found.") == 2050
        first, last = seq_err.index("missing1\n"), seq_err.index("missing4099")
        assert first < last

    def test_jobs_byte_identical(self, tmp_path):
        seq_blobs, seq_err = self._run(tmp_path, "seq", jobs=1)
        par_blobs, par_err = self._run(tmp_path, "par", jobs=4)
        for name in self.ARTIFACTS:
            assert par_blobs[name] == seq_blobs[name], name
        assert par_err == seq_err
        # the dataset exercised every diagnostic shape
        assert "ERROR: parse error. Broken.java" in seq_err
        assert "WARNING: cannot open" in seq_err
        assert "WARNING: method not found. F0.java\tnoSuchMethod" in seq_err
        assert seq_blobs["corpus.txt"].count(b"label:") > 12
