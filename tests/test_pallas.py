"""Fused attention-pooling kernel vs the XLA reference op.

Runs in Pallas interpreter mode on CPU (same code path the TPU compiles);
the numerical contract is identical either way.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from code2vec_tpu.ops.attention import attention_pool
from code2vec_tpu.ops.pallas_attention import pallas_attention_pool


def random_inputs(B=5, L=37, E=24, seed=0, all_pad_row=False):
    rng = np.random.default_rng(seed)
    ctx = rng.normal(size=(B, L, E)).astype(np.float32)
    mask = (rng.random((B, L)) > 0.4).astype(np.float32)
    mask[:, 0] = 1.0
    if all_pad_row:
        mask[1, :] = 0.0
    a = rng.normal(size=E).astype(np.float32)
    return jnp.asarray(ctx), jnp.asarray(mask), jnp.asarray(a)


class TestForward:
    @pytest.mark.parametrize("shape", [(5, 37, 24), (8, 128, 128), (3, 200, 100), (1, 1, 8)])
    def test_matches_xla_op(self, shape):
        B, L, E = shape
        ctx, mask, a = random_inputs(B, L, E)
        cv_ref, w_ref = attention_pool(ctx, mask, a)
        cv_k, w_k = pallas_attention_pool(ctx, mask, a)
        np.testing.assert_allclose(np.asarray(cv_k), np.asarray(cv_ref), rtol=1e-5, atol=1e-5)
        np.testing.assert_allclose(np.asarray(w_k), np.asarray(w_ref), rtol=1e-5, atol=1e-6)

    def test_padding_rows_invisible(self):
        # B=5 pads to block 8; L=37 pads to 128 — outputs must be unaffected
        ctx, mask, a = random_inputs(5, 37, 16, seed=3)
        cv, w = pallas_attention_pool(ctx, mask, a)
        assert cv.shape == (5, 16) and w.shape == (5, 37)
        np.testing.assert_allclose(np.asarray(w.sum(-1)), 1.0, rtol=1e-5)

    def test_bf16_contexts(self):
        ctx, mask, a = random_inputs(4, 32, 16, seed=5)
        cv_ref, _ = attention_pool(ctx.astype(jnp.bfloat16), mask, a)
        cv_k, _ = pallas_attention_pool(ctx.astype(jnp.bfloat16), mask, a)
        np.testing.assert_allclose(
            np.asarray(cv_k), np.asarray(cv_ref, dtype=np.float32), rtol=2e-2, atol=2e-2
        )


class TestGradients:
    def test_grads_match_xla(self):
        ctx, mask, a = random_inputs(4, 21, 12, seed=7)

        def loss_xla(ctx, a):
            cv, w = attention_pool(ctx, mask, a)
            return jnp.sum(cv**2) + jnp.sum(w * jnp.cos(w))

        def loss_pallas(ctx, a):
            cv, w = pallas_attention_pool(ctx, mask, a)
            return jnp.sum(cv**2) + jnp.sum(w * jnp.cos(w))

        g_ref = jax.grad(loss_xla, argnums=(0, 1))(ctx, a)
        g_k = jax.grad(loss_pallas, argnums=(0, 1))(ctx, a)
        np.testing.assert_allclose(np.asarray(g_k[0]), np.asarray(g_ref[0]), rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(np.asarray(g_k[1]), np.asarray(g_ref[1]), rtol=1e-4, atol=1e-5)

    def test_grads_with_fully_masked_row(self):
        ctx, mask, a = random_inputs(4, 16, 8, seed=9, all_pad_row=True)

        def loss(ctx, a):
            cv, _ = pallas_attention_pool(ctx, mask, a)
            return jnp.sum(cv**2)

        g = jax.grad(loss, argnums=(0, 1))(ctx, a)
        assert np.isfinite(np.asarray(g[0])).all()
        assert np.isfinite(np.asarray(g[1])).all()


class TestDegenerateRows:
    def test_fully_masked_row_matches_xla_exactly(self):
        # regression: the all-masked row must softmax uniformly over the
        # REAL bag length, not the lane-padded one
        ctx, mask, a = random_inputs(4, 37, 16, seed=11, all_pad_row=True)
        cv_ref, w_ref = attention_pool(ctx, mask, a)
        cv_k, w_k = pallas_attention_pool(ctx, mask, a)
        np.testing.assert_allclose(np.asarray(w_k[1]), np.asarray(w_ref[1]), rtol=1e-5)
        np.testing.assert_allclose(np.asarray(cv_k[1]), np.asarray(cv_ref[1]), rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(float(w_k[1].sum()), 1.0, rtol=1e-5)


class TestMeshGate:
    def test_pallas_plus_ctx_axis_rejected(self, tmp_path):
        # data/model mesh axes compose with the kernel (custom_partitioning
        # shards the batch dim; TestPallasOnMesh), but a ctx-sharded bag
        # needs the streaming-softmax path the kernel doesn't implement
        from code2vec_tpu.data.reader import load_corpus
        from code2vec_tpu.data.synth import SPECS, generate_corpus_files
        from code2vec_tpu.train.config import TrainConfig
        from code2vec_tpu.train.loop import train

        paths = generate_corpus_files(tmp_path, SPECS["tiny"])
        data = load_corpus(paths["corpus"], paths["path_idx"], paths["terminal_idx"])
        cfg = TrainConfig(use_pallas=True, context_axis=2, max_epoch=1)
        with pytest.raises(ValueError, match="use_pallas with context_axis"):
            train(cfg, data)

    def test_pallas_plus_data_axis_trains(self, tmp_path):
        from code2vec_tpu.data.reader import load_corpus
        from code2vec_tpu.data.synth import SPECS, generate_corpus_files
        from code2vec_tpu.train.config import TrainConfig
        from code2vec_tpu.train.loop import train

        paths = generate_corpus_files(tmp_path, SPECS["tiny"])
        data = load_corpus(paths["corpus"], paths["path_idx"], paths["terminal_idx"])
        cfg = TrainConfig(use_pallas=True, data_axis=2, max_epoch=1,
                          batch_size=32, max_path_length=16, encode_size=16,
                          terminal_embed_size=8, path_embed_size=8)
        res = train(cfg, data)
        assert res.epochs_run == 1


class TestEndToEnd:
    def test_training_with_pallas_model(self, tmp_path):
        from code2vec_tpu.data.reader import load_corpus
        from code2vec_tpu.data.synth import SPECS, generate_corpus_files
        from code2vec_tpu.train.config import TrainConfig
        from code2vec_tpu.train.loop import train

        paths = generate_corpus_files(tmp_path, SPECS["tiny"])
        data = load_corpus(paths["corpus"], paths["path_idx"], paths["terminal_idx"])
        cfg = TrainConfig(
            max_epoch=2,
            batch_size=32,
            encode_size=32,
            terminal_embed_size=16,
            path_embed_size=16,
            max_path_length=16,
            print_sample_cycle=0,
            use_pallas=True,
        )
        res = train(cfg, data)
        assert np.isfinite(res.history[-1]["train_loss"])
        assert res.final_f1 > 0.0

    def test_training_with_pallas_device_epoch(self, tmp_path):
        """The kernel inside the scanned device-epoch chunk (donated state,
        lax.scan) — the configuration the TPU benchmark exercises with
        BENCH_USE_PALLAS=1."""
        from code2vec_tpu.data.reader import load_corpus
        from code2vec_tpu.data.synth import SPECS, generate_corpus_files
        from code2vec_tpu.train.config import TrainConfig
        from code2vec_tpu.train.loop import train

        paths = generate_corpus_files(tmp_path, SPECS["tiny"])
        data = load_corpus(paths["corpus"], paths["path_idx"], paths["terminal_idx"])
        cfg = TrainConfig(
            max_epoch=1,
            batch_size=32,
            encode_size=32,
            terminal_embed_size=16,
            path_embed_size=16,
            max_path_length=16,
            print_sample_cycle=0,
            use_pallas=True,
            device_epoch=True,
            device_chunk_batches=2,
        )
        res = train(cfg, data)
        assert np.isfinite(res.history[-1]["train_loss"])


class TestPallasOnMesh:
    """--use_pallas composed with data/model mesh axes: the kernel's
    custom_partitioning rule shards the batch dim instead of replicating
    the Mosaic call behind an all-gather."""

    def test_matches_xla_path_on_mesh(self):
        from code2vec_tpu.models.code2vec import Code2VecConfig
        from code2vec_tpu.parallel.mesh import make_mesh
        from code2vec_tpu.parallel.shardings import shard_batch, shard_state
        from code2vec_tpu.parallel.step import make_parallel_train_step
        from code2vec_tpu.train.config import TrainConfig
        from code2vec_tpu.train.step import create_train_state

        mesh = make_mesh(data=4, model=2, ctx=1)
        rng = np.random.default_rng(0)
        B, L = 16, 24
        base = dict(
            terminal_count=60, path_count=50, label_count=9,
            terminal_embed_size=8, path_embed_size=8, encode_size=16,
            dropout_prob=0.0,
        )
        batch = {
            "ids": np.arange(B, dtype=np.int64),
            "starts": rng.integers(1, 60, (B, L)).astype(np.int32),
            "paths": rng.integers(1, 50, (B, L)).astype(np.int32),
            "ends": rng.integers(1, 60, (B, L)).astype(np.int32),
            "labels": rng.integers(0, 9, B).astype(np.int32),
            "example_mask": np.ones(B, np.float32),
        }
        batch["starts"][:, L // 2:] = 0

        losses = {}
        for use_pallas in (False, True):
            mc = Code2VecConfig(**base, use_pallas=use_pallas)
            tc = TrainConfig(batch_size=B, max_path_length=L)
            state = create_train_state(tc, mc, jax.random.PRNGKey(0), batch)
            state = shard_state(mesh, state)
            cw = jnp.ones(mc.label_count, jnp.float32)
            step = make_parallel_train_step(mc, cw, mesh, state)
            device_batch = shard_batch(mesh, batch)
            state, loss = step(state, device_batch)
            state, loss2 = step(state, device_batch)
            losses[use_pallas] = (float(loss), float(loss2))
        np.testing.assert_allclose(losses[False], losses[True], rtol=2e-5)
