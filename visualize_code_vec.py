"""Drop-in shim matching the reference's ``python visualize_code_vec.py``
entry (reference: visualize_code_vec.py:1-23); the implementation lives in
:mod:`code2vec_tpu.visualize`.
"""

from code2vec_tpu.visualize import main

if __name__ == "__main__":
    main()
