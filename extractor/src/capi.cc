// C API for in-process use from Python via ctypes (pybind11 is not
// available in this environment; the CPython-visible surface is plain C).
//
// One call parses a Java source buffer and extracts all (or one) method's
// path-contexts, returning a single malloc'd UTF-8 blob:
//
//   corpus-format records (SURVEY.md §2.4)
//   "===TERMINALS===\n" <index>\t<name> lines
//   "===PATHS===\n"     <index>\t<name> lines
//
// The caller frees with c2v_free. Errors return NULL with the message
// available via c2v_last_error (thread-local).

#include <cstdlib>
#include <cstring>
#include <sstream>
#include <string>

#include "extract.h"
#include "parser.h"

namespace {
thread_local std::string g_last_error;

char* dup_string(const std::string& s) {
  char* out = static_cast<char*>(std::malloc(s.size() + 1));
  if (out) std::memcpy(out, s.c_str(), s.size() + 1);
  return out;
}
}  // namespace

extern "C" {

const char* c2v_last_error() { return g_last_error.c_str(); }

void c2v_free(char* p) { std::free(p); }

char* c2v_extract_source(const char* source, const char* method_name,
                         int max_length, int max_width,
                         int normalize_string, int normalize_char,
                         int normalize_int, int normalize_double) {
  try {
    c2v::ExtractConfig config;
    config.max_length = max_length;
    config.max_width = max_width;
    config.normalize_string_literal = normalize_string != 0;
    config.normalize_char_literal = normalize_char != 0;
    config.normalize_int_literal = normalize_int != 0;
    config.normalize_double_literal = normalize_double != 0;

    auto cu = c2v::parse_compilation_unit(source);
    c2v::Vocabs vocabs;
    auto methods = c2v::extract_features(
        *cu, method_name ? method_name : "*", vocabs, config);

    std::ostringstream out;
    int id = 0;
    for (const auto& mf : methods) {
      out << "#" << id++ << "\n";
      out << "label:" << mf.method_name << "\n";
      out << "paths:\n";
      for (const auto& f : mf.features)
        out << f.start << "\t" << f.path << "\t" << f.end << "\n";
      out << "vars:\n";
      for (auto it = mf.env.vars.variables.rbegin();
           it != mf.env.vars.variables.rend(); ++it)
        out << it->name << "\t" << it->id << "\n";
      for (auto it = mf.env.labels.variables.rbegin();
           it != mf.env.labels.variables.rend(); ++it)
        out << it->name << "\t" << it->id << "\n";
      out << "\n";
    }
    out << "===TERMINALS===\n";
    for (const auto& [name, index] : vocabs.terminals())
      out << index << "\t" << name << "\n";
    out << "===PATHS===\n";
    for (const auto& [name, index] : vocabs.paths())
      out << index << "\t" << name << "\n";
    return dup_string(out.str());
  } catch (const std::exception& e) {
    g_last_error = e.what();
    return nullptr;
  }
}

}  // extern "C"

// ---------------------------------------------------------------------------
// Native corpus.txt parser: the numeric path-triple lines are ~98% of a
// corpus file's bytes, and parsing them in Python dominates cold-start at
// top11 scale (605k methods, SURVEY.md §6). This parses the whole file into
// flat arrays with the exact record semantics of the Python state machine
// (code2vec_tpu/formats/corpus_io.py, itself mirroring the reference's
// model/dataset_reader.py:72-128). String fields come back in one packed
// blob the Python side splits:
//   headers: per record "<label>\x1f<flag><source>\x1e"  (flag '1' = class:
//            line present, '0' = absent)
//   vars:    per record ("<original>\x1f<alias>\x1d")* "\x1e"
// Raw indices are returned unshifted; the caller applies the @question +1
// shift (model/dataset_reader.py:113-115).

#include <cstdint>
#include <fstream>
#include <vector>

extern "C" {

typedef struct {
  int64_t n_records;
  int64_t n_contexts;
  int32_t* starts;
  int32_t* paths;
  int32_t* ends;
  int64_t* row_splits;  // [n_records + 1]
  int64_t* ids;         // [n_records], -1 when the record had no #id line
  char* headers;
  int64_t headers_len;
  char* vars;
  int64_t vars_len;
} C2vCorpus;

void c2v_free_corpus(C2vCorpus* c) {
  if (!c) return;
  std::free(c->starts);
  std::free(c->paths);
  std::free(c->ends);
  std::free(c->row_splits);
  std::free(c->ids);
  std::free(c->headers);
  std::free(c->vars);
  std::free(c);
}

C2vCorpus* c2v_parse_corpus(const char* path) {
  try {
    std::ifstream f(path, std::ios::binary);
    if (!f) {
      g_last_error = std::string("cannot open ") + path;
      return nullptr;
    }
    f.seekg(0, std::ios::end);
    const std::streamoff size = f.tellg();
    f.seekg(0, std::ios::beg);
    std::string buf;
    buf.resize(static_cast<size_t>(size));
    if (size > 0 && !f.read(buf.data(), size)) {
      g_last_error = std::string("short read on ") + path;
      return nullptr;
    }

    std::vector<int32_t> starts, paths, ends;
    std::vector<int64_t> row_splits{0}, ids;
    std::string headers, vars;

    enum Mode { HEADER, PATHS, VARS };
    Mode mode = HEADER;
    bool in_record = false;
    int64_t record_id = -1;
    std::string label, source;
    bool has_source = false;
    std::string record_vars;

    auto finalize = [&]() {
      if (!in_record) return;
      row_splits.push_back(static_cast<int64_t>(starts.size()));
      ids.push_back(record_id);
      headers += label;
      headers += '\x1f';
      headers += has_source ? '1' : '0';
      headers += source;
      headers += '\x1e';
      vars += record_vars;
      vars += '\x1e';
      in_record = false;
      record_id = -1;
      label.clear();
      source.clear();
      has_source = false;
      record_vars.clear();
      mode = HEADER;
    };

    const char* p = buf.data();
    const char* bufend = p + buf.size();
    while (p < bufend) {
      const char* nl = static_cast<const char*>(
          std::memchr(p, '\n', static_cast<size_t>(bufend - p)));
      const char* line_end = nl ? nl : bufend;
      // trim " \r\t" both ends (python: line.strip(" \r\n\t"))
      const char* s = p;
      const char* e = line_end;
      while (s < e && (*s == ' ' || *s == '\r' || *s == '\t')) ++s;
      while (e > s && (e[-1] == ' ' || e[-1] == '\r' || e[-1] == '\t')) --e;
      size_t len = static_cast<size_t>(e - s);

      if (len == 0) {
        finalize();
      } else {
        if (!in_record) in_record = true;
        if (s[0] == '#') {
          // python parity: int(line[1:]) — leading/trailing whitespace ok,
          // trailing garbage is not (reader rejects "#12abc")
          char* q = nullptr;
          record_id = std::strtoll(s + 1, &q, 10);
          while (q < e && (*q == ' ' || *q == '\t' || *q == '\r')) ++q;
          if (q == s + 1 || q != e) {
            g_last_error = "malformed record id line: " + std::string(s, len);
            return nullptr;
          }
        } else if (len >= 6 && std::memcmp(s, "label:", 6) == 0) {
          label.assign(s + 6, len - 6);
        } else if (len >= 6 && std::memcmp(s, "class:", 6) == 0) {
          source.assign(s + 6, len - 6);
          has_source = true;
        } else if (len >= 4 && std::memcmp(s, "doc:", 4) == 0) {
          // parsed and discarded (reference: dataset_reader.py:109-110)
        } else if (len >= 6 && std::memcmp(s, "paths:", 6) == 0) {
          mode = PATHS;
        } else if (len >= 5 && std::memcmp(s, "vars:", 5) == 0) {
          mode = VARS;
        } else if (mode == PATHS) {
          // python parity: int(line.split("\t")[k]) for k in 0..2 — the
          // separator must be a tab and each field a complete integer;
          // trailing columns are tolerated, space-separated or intra-field
          // garbage is not (corruption must not become silent zeros)
          long vals[3];
          const char* fs = s;
          bool ok = true;
          for (int k = 0; k < 3; ++k) {
            const char* fe = static_cast<const char*>(
                std::memchr(fs, '\t', static_cast<size_t>(e - fs)));
            if (!fe) fe = e;
            if (k < 2 && fe == e) {  // fewer than 3 columns: IndexError
              ok = false;
              break;
            }
            char* q = nullptr;
            vals[k] = std::strtol(fs, &q, 10);
            const char* qe = q;
            while (qe < fe && (*qe == ' ' || *qe == '\r')) ++qe;
            if (q == fs || q > fe || qe != fe) {
              ok = false;
              break;
            }
            fs = fe + 1;
          }
          if (!ok) {
            g_last_error = "malformed path-context line: " +
                           std::string(s, len);
            return nullptr;
          }
          starts.push_back(static_cast<int32_t>(vals[0]));
          paths.push_back(static_cast<int32_t>(vals[1]));
          ends.push_back(static_cast<int32_t>(vals[2]));
        } else if (mode == VARS) {
          const char* tab = static_cast<const char*>(
              std::memchr(s, '\t', len));
          if (!tab) {
            // Python raises IndexError on a tab-less vars line
            g_last_error = "malformed vars line: " + std::string(s, len);
            return nullptr;
          }
          const char* v2 = tab + 1;
          const char* tab2 = static_cast<const char*>(
              std::memchr(v2, '\t', static_cast<size_t>(e - v2)));
          const char* v2end = tab2 ? tab2 : e;
          record_vars.append(s, static_cast<size_t>(tab - s));
          record_vars += '\x1f';
          record_vars.append(v2, static_cast<size_t>(v2end - v2));
          record_vars += '\x1d';
        }
      }
      if (!nl) break;
      p = nl + 1;
    }
    finalize();  // trailing record without a final blank line

    auto* out = static_cast<C2vCorpus*>(std::malloc(sizeof(C2vCorpus)));
    if (!out) { g_last_error = "out of memory"; return nullptr; }
    auto copy_i32 = [](const std::vector<int32_t>& v) {
      auto* m = static_cast<int32_t*>(std::malloc(v.size() * 4 + 4));
      if (m) std::memcpy(m, v.data(), v.size() * 4);
      return m;
    };
    auto copy_i64 = [](const std::vector<int64_t>& v) {
      auto* m = static_cast<int64_t*>(std::malloc(v.size() * 8 + 8));
      if (m) std::memcpy(m, v.data(), v.size() * 8);
      return m;
    };
    out->n_records = static_cast<int64_t>(ids.size());
    out->n_contexts = static_cast<int64_t>(starts.size());
    out->starts = copy_i32(starts);
    out->paths = copy_i32(paths);
    out->ends = copy_i32(ends);
    out->row_splits = copy_i64(row_splits);
    out->ids = copy_i64(ids);
    out->headers = dup_string(headers);
    out->headers_len = static_cast<int64_t>(headers.size());
    out->vars = dup_string(vars);
    out->vars_len = static_cast<int64_t>(vars.size());
    if (!out->starts || !out->paths || !out->ends || !out->row_splits ||
        !out->ids || !out->headers || !out->vars) {
      g_last_error = "out of memory";
      c2v_free_corpus(out);
      return nullptr;
    }
    return out;
  } catch (const std::exception& e) {
    g_last_error = e.what();
    return nullptr;
  }
}

}  // extern "C"
