// C API for in-process use from Python via ctypes (pybind11 is not
// available in this environment; the CPython-visible surface is plain C).
//
// One call parses a Java source buffer and extracts all (or one) method's
// path-contexts, returning a single malloc'd UTF-8 blob:
//
//   corpus-format records (SURVEY.md §2.4)
//   "===TERMINALS===\n" <index>\t<name> lines
//   "===PATHS===\n"     <index>\t<name> lines
//
// The caller frees with c2v_free. Errors return NULL with the message
// available via c2v_last_error (thread-local).

#include <cstdlib>
#include <cstring>
#include <sstream>
#include <string>

#include "extract.h"
#include "parser.h"

namespace {
thread_local std::string g_last_error;

char* dup_string(const std::string& s) {
  char* out = static_cast<char*>(std::malloc(s.size() + 1));
  if (out) std::memcpy(out, s.c_str(), s.size() + 1);
  return out;
}
}  // namespace

extern "C" {

const char* c2v_last_error() { return g_last_error.c_str(); }

void c2v_free(char* p) { std::free(p); }

char* c2v_extract_source(const char* source, const char* method_name,
                         int max_length, int max_width,
                         int normalize_string, int normalize_char,
                         int normalize_int, int normalize_double) {
  try {
    c2v::ExtractConfig config;
    config.max_length = max_length;
    config.max_width = max_width;
    config.normalize_string_literal = normalize_string != 0;
    config.normalize_char_literal = normalize_char != 0;
    config.normalize_int_literal = normalize_int != 0;
    config.normalize_double_literal = normalize_double != 0;

    auto cu = c2v::parse_compilation_unit(source);
    c2v::Vocabs vocabs;
    auto methods = c2v::extract_features(
        *cu, method_name ? method_name : "*", vocabs, config);

    std::ostringstream out;
    int id = 0;
    for (const auto& mf : methods) {
      out << "#" << id++ << "\n";
      out << "label:" << mf.method_name << "\n";
      out << "paths:\n";
      for (const auto& f : mf.features)
        out << f.start << "\t" << f.path << "\t" << f.end << "\n";
      out << "vars:\n";
      for (auto it = mf.env.vars.variables.rbegin();
           it != mf.env.vars.variables.rend(); ++it)
        out << it->name << "\t" << it->id << "\n";
      for (auto it = mf.env.labels.variables.rbegin();
           it != mf.env.labels.variables.rend(); ++it)
        out << it->name << "\t" << it->id << "\n";
      out << "\n";
    }
    out << "===TERMINALS===\n";
    for (const auto& [name, index] : vocabs.terminals())
      out << index << "\t" << name << "\n";
    out << "===PATHS===\n";
    for (const auto& [name, index] : vocabs.paths())
      out << index << "\t" << name << "\n";
    return dup_string(out.str());
  } catch (const std::exception& e) {
    g_last_error = e.what();
    return nullptr;
  }
}

}  // extern "C"
