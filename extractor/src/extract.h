// Path-context extraction: AST normalization/anonymization, leaf-pair path
// enumeration, vocab interning. Faithful reimplementation of the reference
// Scala pipeline (create_path_contexts.ipynb cells 4-10); each piece cites
// its cell.

#pragma once

#include <map>
#include <memory>
#include <optional>
#include <string>
#include <vector>

#include "ast.h"

namespace c2v {

// ---- normalized AST (ipynb cell5 `AstNode`) ---------------------------
struct ENode {
  std::string name;
  std::optional<std::string> terminal;
  std::vector<std::unique_ptr<ENode>> children;
};
using ENodePtr = std::unique_ptr<ENode>;

// ---- extraction config (ipynb cell6 `ExtractConfig`) ------------------
struct ExtractConfig {
  bool normalize_string_literal = true;
  bool normalize_char_literal = true;
  bool normalize_int_literal = false;
  bool normalize_double_literal = true;
  int max_length = 8;
  int max_width = 3;
};

// ---- anonymization environment (ipynb cell6 `Env`/`VarEnv`) -----------
struct Variable {
  std::string id;    // e.g. "@var_0"
  std::string name;  // original source name
};

struct Env {
  explicit Env(std::string s) : space(std::move(s)) {}
  std::string space;  // "var" | "method" | "label"
  int next_index = 0;
  std::vector<Variable> variables;  // encounter order (the reference's list
                                    // is prepend-order; writers iterate in
                                    // reverse for output parity)
  Variable fresh(const std::string& original);
};

struct VarEnv {
  Env vars{"var"};
  Env methods{"method"};
  Env labels{"label"};
};

// ---- vocab interning (ipynb cell7 `Vocabs`) ---------------------------
// Insertion-ordered, 1-based; terminals lowercased to shrink the vocab.
class Vocabs {
 public:
  int terminal_index(const std::string& terminal);
  // deferred-interning path: the caller already lowercased (worker side)
  int terminal_index_lowered(const std::string& terminal);
  int path_index(const std::string& path);
  const std::vector<std::pair<std::string, int>>& terminals() const {
    return terminal_list_;
  }
  const std::vector<std::pair<std::string, int>>& paths() const {
    return path_list_;
  }

 private:
  std::map<std::string, int> terminal_map_;
  std::map<std::string, int> path_map_;
  std::vector<std::pair<std::string, int>> terminal_list_;
  std::vector<std::pair<std::string, int>> path_list_;
};

// ---- per-method extraction result (ipynb cell10) ----------------------
struct Feature {
  int start, path, end;
};

struct MethodFeatures {
  std::vector<Feature> features;
  VarEnv env;
  std::string method_name;     // original (label line)
  std::string method_source;   // raw decl text (method_declarations.txt)
};

// ---- vocab-free variant (parallel extraction) -------------------------
// Workers extract to strings; a sequential committer interns in the same
// order the single-threaded path would (all terminals in encounter order,
// then paths in pair order), so vocab files stay byte-identical.
struct FeatureStr {
  int start_terminal, end_terminal;  // indexes into terminal_names
  std::string path;
};

struct MethodFeaturesStr {
  std::vector<std::string> terminal_names;  // lowercased, encounter order
  std::vector<FeatureStr> features;
  VarEnv env;
  std::string method_name;
  std::string method_source;
};

// Trivial-method filter (ipynb cell4 `isIgnorableMethod`).
bool is_ignorable_method(const JNode& method);

// Normalize/anonymize one method declaration (ipynb cell6 `extractAST`).
ENodePtr extract_ast(const JNode& method, VarEnv& env, const ExtractConfig& config);

// All matching methods of a compilation unit -> features
// (ipynb cell10 `extractFeature`; method_name "*" matches everything,
// otherwise case-insensitive name match).
std::vector<MethodFeatures> extract_features(const JNode& cu,
                                             const std::string& method_name,
                                             Vocabs& vocabs,
                                             const ExtractConfig& config);

// Vocab-free extraction (thread-safe: touches no shared state) plus the
// sequential interning step. extract_features == intern_features applied
// to extract_features_str, in order.
std::vector<MethodFeaturesStr> extract_features_str(
    const JNode& cu, const std::string& method_name,
    const ExtractConfig& config);

MethodFeatures intern_features(MethodFeaturesStr mf, Vocabs& vocabs);

}  // namespace c2v
