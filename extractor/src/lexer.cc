#include "lexer.h"

#include <cctype>
#include <cstring>

namespace c2v {

namespace {

bool ident_start(char c) { return std::isalpha(static_cast<unsigned char>(c)) || c == '_' || c == '$' || (static_cast<unsigned char>(c) >= 0x80); }
bool ident_part(char c) { return ident_start(c) || std::isdigit(static_cast<unsigned char>(c)); }

// Multi-char operators, longest first within each leading char.
const char* kOps3[] = {">>>=", nullptr};
const char* kOps2[] = {"<<=", ">>=", ">>>", "->",  "::",  "==", "!=", "<=",
                       ">=",  "&&",  "||", "++",  "--",  "+=", "-=", "*=",
                       "/=",  "%=",  "&=", "|=",  "^=",  "<<", ">>", nullptr};

}  // namespace

Lexer::Lexer(const std::string& src) { run(src); }

void Lexer::run(const std::string& src) {
  size_t i = 0, n = src.size();
  int line = 1;
  while (i < n) {
    char c = src[i];
    if (c == '\n') { ++line; ++i; continue; }
    if (std::isspace(static_cast<unsigned char>(c))) { ++i; continue; }
    // comments (stripped — parity with ipynb cell6's comment filter)
    if (c == '/' && i + 1 < n && src[i + 1] == '/') {
      while (i < n && src[i] != '\n') ++i;
      continue;
    }
    if (c == '/' && i + 1 < n && src[i + 1] == '*') {
      i += 2;
      while (i + 1 < n && !(src[i] == '*' && src[i + 1] == '/')) {
        if (src[i] == '\n') ++line;
        ++i;
      }
      i = (i + 1 < n) ? i + 2 : n;
      continue;
    }
    if (ident_start(c)) {
      size_t start = i;
      while (i < n && ident_part(src[i])) ++i;
      tokens_.push_back({Tok::kIdent, src.substr(start, i - start), line, start, i});
      continue;
    }
    if (std::isdigit(static_cast<unsigned char>(c)) ||
        (c == '.' && i + 1 < n && std::isdigit(static_cast<unsigned char>(src[i + 1])))) {
      size_t start = i;
      bool is_float = false;
      if (c == '0' && i + 1 < n && (src[i + 1] == 'x' || src[i + 1] == 'X')) {
        i += 2;
        while (i < n && (std::isxdigit(static_cast<unsigned char>(src[i])) || src[i] == '_')) ++i;
      } else if (c == '0' && i + 1 < n && (src[i + 1] == 'b' || src[i + 1] == 'B')) {
        i += 2;
        while (i < n && (src[i] == '0' || src[i] == '1' || src[i] == '_')) ++i;
      } else {
        while (i < n && (std::isdigit(static_cast<unsigned char>(src[i])) || src[i] == '_')) ++i;
        if (i < n && src[i] == '.') {
          is_float = true;
          ++i;
          while (i < n && (std::isdigit(static_cast<unsigned char>(src[i])) || src[i] == '_')) ++i;
        }
        if (i < n && (src[i] == 'e' || src[i] == 'E')) {
          is_float = true;
          ++i;
          if (i < n && (src[i] == '+' || src[i] == '-')) ++i;
          while (i < n && std::isdigit(static_cast<unsigned char>(src[i]))) ++i;
        }
      }
      Tok kind = is_float ? Tok::kDouble : Tok::kInt;
      if (i < n) {
        if (src[i] == 'l' || src[i] == 'L') { kind = Tok::kLong; ++i; }
        else if (src[i] == 'f' || src[i] == 'F' || src[i] == 'd' || src[i] == 'D') { kind = Tok::kDouble; ++i; }
      }
      tokens_.push_back({kind, src.substr(start, i - start), line, start, i});
      continue;
    }
    if (c == '"') {
      if (i + 2 < n && src[i + 1] == '"' && src[i + 2] == '"') {
        // Java 15 text block: """ ... """ — one kString token, so it flows
        // into StringLiteralExpr and the @string_literal normalization
        size_t start = i;
        i += 3;
        while (i + 2 < n &&
               !(src[i] == '"' && src[i + 1] == '"' && src[i + 2] == '"')) {
          if (src[i] == '\\' && i + 1 < n) ++i;
          if (src[i] == '\n') ++line;
          ++i;
        }
        if (i + 2 >= n)
          throw LexError("line " + std::to_string(line) +
                         ": unterminated text block");
        i += 3;
        // terminals flow to line-oriented surfaces (terminal_idxs.txt, the
        // ctypes blob) — keep the lexeme single-line by escaping newlines
        std::string flat;
        flat.reserve(i - start);
        for (size_t k = start; k < i; ++k) {
          if (src[k] == '\n') flat += "\\n";
          else if (src[k] != '\r') flat += src[k];
        }
        tokens_.push_back({Tok::kString, std::move(flat), line, start, i});
        continue;
      }
      size_t start = i++;
      while (i < n && src[i] != '"') {
        if (src[i] == '\\' && i + 1 < n) ++i;
        if (src[i] == '\n') ++line;
        ++i;
      }
      if (i < n) ++i;
      tokens_.push_back({Tok::kString, src.substr(start, i - start), line, start, i});
      continue;
    }
    if (c == '\'') {
      size_t start = i++;
      while (i < n && src[i] != '\'') {
        if (src[i] == '\\' && i + 1 < n) ++i;
        ++i;
      }
      if (i < n) ++i;
      tokens_.push_back({Tok::kChar, src.substr(start, i - start), line, start, i});
      continue;
    }
    // operators / punctuation: longest match
    bool matched = false;
    for (const char** ops : {kOps3, kOps2}) {
      for (int k = 0; ops[k]; ++k) {
        size_t len = std::strlen(ops[k]);
        if (src.compare(i, len, ops[k]) == 0) {
          tokens_.push_back({Tok::kPunct, ops[k], line, i, i + len});
          i += len;
          matched = true;
          break;
        }
      }
      if (matched) break;
    }
    if (matched) continue;
    tokens_.push_back({Tok::kPunct, std::string(1, c), line, i, i + 1});
    ++i;
  }
  tokens_.push_back({Tok::kEnd, "", line, n, n});
}

}  // namespace c2v
