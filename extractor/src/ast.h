// AST node for the Java path-context extractor.
//
// Node type names follow javaparser's class names (the reference extraction
// pipeline is built on javaparser 3.6 — create_path_contexts.ipynb cell1)
// so that path strings like "SimpleName<UP>MethodCallExpr<DOWN>NameExpr"
// carry the same vocabulary of node kinds. Child ordering is source order
// within each construct (documented per-production in parser.cc); this can
// differ from javaparser's metamodel ordering in corner cases, which
// changes some path strings but not the extraction semantics.

#pragma once

#include <memory>
#include <string>
#include <vector>

namespace c2v {

struct JNode {
  std::string type;          // javaparser-style class name, e.g. "MethodCallExpr"
  std::string text;          // identifier/literal source text where applicable
  std::string op;            // operator enum name for Unary/Binary/Assign
  bool is_var_args = false;  // Parameter only
  std::vector<std::unique_ptr<JNode>> children;

  JNode() = default;
  explicit JNode(std::string t) : type(std::move(t)) {}
  JNode(std::string t, std::string s) : type(std::move(t)), text(std::move(s)) {}

  JNode* add(std::unique_ptr<JNode> child) {
    children.push_back(std::move(child));
    return children.back().get();
  }
  bool leaf() const { return children.empty(); }
};

using JNodePtr = std::unique_ptr<JNode>;

inline JNodePtr make(std::string type) { return std::make_unique<JNode>(std::move(type)); }
inline JNodePtr make(std::string type, std::string text) {
  return std::make_unique<JNode>(std::move(type), std::move(text));
}

// Pretty-printed source text of a node, used as the terminal symbol for
// leaf Expression/Name/Type nodes (ipynb cell6: node.toString(prettyPrintConfig)).
std::string node_source(const JNode& n);

}  // namespace c2v
