#include "extract.h"

#include <algorithm>
#include <cctype>
#include <set>
#include <stdexcept>

namespace c2v {

namespace {

const std::set<std::string> kObjectMethods = {"clone", "equals", "finalize",
                                              "hashCode", "toString"};

std::string lower(std::string s) {
  std::transform(s.begin(), s.end(), s.begin(),
                 [](unsigned char c) { return std::tolower(c); });
  return s;
}

const JNode* find_child(const JNode& n, const std::string& type) {
  for (const auto& c : n.children)
    if (c->type == type) return c.get();
  return nullptr;
}

int count_children(const JNode& n, const std::string& type) {
  int k = 0;
  for (const auto& c : n.children) k += c->type == type;
  return k;
}

// immutable binding list (ipynb cell5 `ParseContext`): a new cons cell per
// declaration, structurally shared, dropped on scope exit
struct Binding {
  std::string space;  // "var" | "method" | "label"
  std::string name;
  std::string id;
  std::shared_ptr<const Binding> next;
  bool from_pattern = false;  // introduced by a PatternExpr (arm-scoped)
};
using Ctx = std::shared_ptr<const Binding>;

Ctx bind(const Ctx& ctx, const std::string& space, const Variable& v,
         bool from_pattern = false) {
  return std::make_shared<const Binding>(
      Binding{space, v.name, v.id, ctx, from_pattern});
}

std::string lookup(const Ctx& ctx, const std::string& space,
                   const std::string& name) {
  for (const Binding* b = ctx.get(); b; b = b->next.get())
    if (b->space == space && b->name == name) return b->id;
  return name;  // unresolved names keep their own text (cell5 getOrElse)
}

// node-kind classification for the default/leaf case of extractAST (cell6)
const std::set<std::string> kExpressionKinds = {
    "NameExpr", "MethodCallExpr", "FieldAccessExpr", "ObjectCreationExpr",
    "ArrayCreationExpr", "ArrayAccessExpr", "ArrayInitializerExpr",
    "CastExpr", "InstanceOfExpr", "EnclosedExpr", "ConditionalExpr",
    "UnaryExpr", "BinaryExpr", "AssignExpr", "LambdaExpr",
    "MethodReferenceExpr", "ClassExpr", "TypeExpr", "VariableDeclarationExpr",
    "MarkerAnnotationExpr", "SingleMemberAnnotationExpr",
    "NormalAnnotationExpr", "StringLiteralExpr", "CharLiteralExpr",
    "IntegerLiteralExpr", "LongLiteralExpr", "DoubleLiteralExpr",
    "BooleanLiteralExpr", "NullLiteralExpr", "ThisExpr", "SuperExpr",
    "SwitchExpr", "PatternExpr"};
const std::set<std::string> kTypeKinds = {
    "PrimitiveType", "VoidType", "ClassOrInterfaceType", "ArrayType",
    "WildcardType", "UnionType", "IntersectionType", "TypeParameter",
    "VarType"};  // Java 10 'var' — a leaf type whose terminal is "var"
const std::set<std::string> kNameKinds = {"Name", "SimpleName"};
const std::set<std::string> kLeafStatementKinds = {
    "BreakStmt", "ReturnStmt", "ContinueStmt", "SwitchEntryStmt", "EmptyStmt",
    "ExplicitConstructorInvocationStmt"};  // zero-arg this()/super()

// scope-closing node types (cell6's big isInstanceOf disjunction, extended
// with the modern-Java declarations the reference's javaparser predates)
const std::set<std::string> kScopeClosers = {
    "BlockStmt", "LambdaExpr", "MethodDeclaration", "ConstructorDeclaration",
    "ClassOrInterfaceDeclaration", "EnumDeclaration",
    "EnumConstantDeclaration", "AnnotationDeclaration",
    "AnnotationMemberDeclaration", "TryStmt", "CatchClause",
    "RecordDeclaration", "CompactConstructorDeclaration"};

ENodePtr enode(std::string name) {
  auto n = std::make_unique<ENode>();
  n->name = std::move(name);
  return n;
}
ENodePtr enode_terminal(std::string name, std::string terminal) {
  auto n = enode(std::move(name));
  n->terminal = std::move(terminal);
  return n;
}

struct Extractor {
  VarEnv& env;
  const ExtractConfig& config;

  using Result = std::pair<ENodePtr, Ctx>;

  // evaluate children in order, chaining contexts (cell6 extractAstList);
  // `special` intercepts specific children (the SimpleName-replacement
  // pattern of Parameter/VariableDeclarator/MethodDeclaration/...)
  template <typename Handler>
  std::pair<std::vector<ENodePtr>, Ctx> eval_list(const JNode& n, Ctx ctx,
                                                  Handler&& special) {
    std::vector<ENodePtr> out;
    Ctx current = ctx;
    for (const auto& child : n.children) {
      Result r = special(*child, current);
      out.push_back(std::move(r.first));
      current = r.second;
    }
    return {std::move(out), current};
  }

  std::pair<std::vector<ENodePtr>, Ctx> eval_children(const JNode& n, Ctx ctx) {
    return eval_list(n, ctx, [&](const JNode& c, Ctx cur) { return extract(c, cur); });
  }

  Result extract(const JNode& n, Ctx ctx) {
    const std::string& t = n.type;

    // ---- literal normalization (cell6) --------------------------------
    if (t == "StringLiteralExpr" && config.normalize_string_literal)
      return {enode_terminal(t, "@string_literal"), ctx};
    if (t == "CharLiteralExpr" && config.normalize_char_literal)
      return {enode_terminal(t, "@char_literal"), ctx};
    if ((t == "IntegerLiteralExpr" || t == "LongLiteralExpr") &&
        config.normalize_int_literal)
      return {enode_terminal(t, "@int_literal"), ctx};
    if (t == "DoubleLiteralExpr" && config.normalize_double_literal)
      return {enode_terminal(t, "@double_literal"), ctx};

    // ---- parameter anonymization (cell6 `case p: Parameter`) ----------
    if (t == "Parameter") {
      const JNode* name_node = find_child(n, "SimpleName");
      std::string original = name_node ? name_node->text : "";
      Variable alias = env.vars.fresh(original);
      Ctx new_ctx = bind(ctx, "var", alias);
      auto [children, _] = eval_list(n, ctx, [&](const JNode& c, Ctx cur) -> Result {
        if (c.type == "SimpleName")
          return {enode_terminal("SimpleName", alias.id), cur};
        if (kTypeKinds.count(c.type)) {
          auto type_ast = extract(c, cur).first;
          if (n.is_var_args) {
            auto wrapper = enode("VarArgs");
            wrapper->children.push_back(std::move(type_ast));
            return {std::move(wrapper), cur};
          }
          return {std::move(type_ast), cur};
        }
        return extract(c, cur);
      });
      auto ast = enode(t);
      ast->children = std::move(children);
      return {std::move(ast), new_ctx};
    }

    // ---- operator-suffixed nodes (cell6 Unary/Binary/Assign) ----------
    if (t == "UnaryExpr" || t == "BinaryExpr" || t == "AssignExpr") {
      auto [children, new_ctx] = eval_children(n, ctx);
      auto ast = enode(t + ":" + n.op);
      ast->children = std::move(children);
      return {std::move(ast), new_ctx};
    }

    // ---- variable declarator (cell6) ----------------------------------
    if (t == "VariableDeclarator") {
      const JNode* name_node = find_child(n, "SimpleName");
      std::string original = name_node ? name_node->text : "";
      Variable alias = env.vars.fresh(original);
      Ctx new_ctx = bind(ctx, "var", alias);
      auto [children, _] = eval_list(n, ctx, [&](const JNode& c, Ctx cur) -> Result {
        if (c.type == "SimpleName")
          // the reference's handler returns newContext here, so the
          // initializer (a later sibling) sees the fresh binding — Java
          // self-reference semantics
          return {enode_terminal("SimpleName", alias.id), new_ctx};
        return extract(c, cur);
      });
      auto ast = enode(t);
      ast->children = std::move(children);
      return {std::move(ast), new_ctx};
    }

    // ---- pattern binding ('x instanceof Type t', 'case Type t ->') ----
    // anonymized like a declarator; the new binding flows to later siblings
    // through the default case's context chaining, which approximates
    // Java's flow scoping ('cond && t.f()' and the guarded entry body see
    // the alias)
    if (t == "PatternExpr") {
      const JNode* name_node = find_child(n, "SimpleName");
      std::string original = name_node ? name_node->text : "";
      Variable alias = env.vars.fresh(original);
      Ctx new_ctx = bind(ctx, "var", alias, /*from_pattern=*/true);
      auto [children, _] = eval_list(n, ctx, [&](const JNode& c, Ctx cur) -> Result {
        if (c.type == "SimpleName")
          return {enode_terminal("SimpleName", alias.id), cur};
        return extract(c, cur);
      });
      auto ast = enode(t);
      ast->children = std::move(children);
      return {std::move(ast), new_ctx};
    }

    // ---- variable reference (cell6 `case e: NameExpr`) ----------------
    if (t == "NameExpr") {
      const JNode* name_node = find_child(n, "SimpleName");
      std::string name = name_node ? name_node->text : "";
      auto ast = enode(t);
      ast->children.push_back(
          enode_terminal("SimpleName", lookup(ctx, "var", name)));
      return {std::move(ast), ctx};
    }

    // ---- method declaration (cell6) -----------------------------------
    if (t == "MethodDeclaration") {
      const JNode* name_node = find_child(n, "SimpleName");
      std::string original = name_node ? name_node->text : "";
      Variable alias = env.methods.fresh(original);
      Ctx new_ctx = bind(ctx, "method", alias);
      auto [children, _] = eval_list(n, ctx, [&](const JNode& c, Ctx cur) -> Result {
        if (c.type == "SimpleName")
          // params/body (later siblings) see the @method_0 binding, so
          // self-recursion resolves (cell6's recursion-aware comment)
          return {enode_terminal("SimpleName", alias.id), new_ctx};
        return extract(c, cur);
      });
      auto ast = enode(t);
      ast->children = std::move(children);
      return {std::move(ast), ctx};  // close scope
    }

    // ---- method call (cell6) ------------------------------------------
    if (t == "MethodCallExpr") {
      // my AST shape: [scope?, SimpleName, args...] — scope is any non-
      // SimpleName first child
      const JNode* scope = nullptr;
      if (!n.children.empty() && n.children[0]->type != "SimpleName")
        scope = n.children[0].get();
      const JNode* name_node = find_child(n, "SimpleName");
      std::string name = name_node ? name_node->text : "";
      bool self_call =
          scope == nullptr || (scope->type == "ThisExpr" && scope->leaf());
      ENodePtr ast_name =
          self_call ? enode_terminal("SimpleName", lookup(ctx, "method", name))
                    : enode_terminal("SimpleName", name);
      auto [children, _] = eval_list(n, ctx, [&](const JNode& c, Ctx cur) -> Result {
        if (c.type == "SimpleName") {
          auto copy = enode_terminal("SimpleName", *ast_name->terminal);
          return {std::move(copy), cur};
        }
        return extract(c, cur);
      });
      auto ast = enode(t);
      ast->children = std::move(children);
      return {std::move(ast), ctx};  // close scope
    }

    // ---- labeled statement / break / continue (cell6) -----------------
    if (t == "LabeledStmt") {
      const JNode* label_node = find_child(n, "SimpleName");
      std::string label = label_node ? label_node->text : "";
      Variable alias = env.labels.fresh(label);
      Ctx new_ctx = bind(ctx, "label", alias);
      auto [children, final_ctx] =
          eval_list(n, ctx, [&](const JNode& c, Ctx cur) -> Result {
            if (c.type == "SimpleName")
              return {enode_terminal("SimpleName", alias.id), new_ctx};
            return extract(c, cur);
          });
      auto ast = enode(t);
      ast->children = std::move(children);
      return {std::move(ast), final_ctx};  // label stays bound (cell6)
    }
    if (t == "BreakStmt" || t == "ContinueStmt") {
      auto ast = enode(t);
      const JNode* label_node = find_child(n, "SimpleName");
      if (label_node)
        ast->children.push_back(enode_terminal(
            "SimpleName", lookup(ctx, "label", label_node->text)));
      return {std::move(ast), ctx};
    }

    // ---- ternary with Condition wrapper (cell6) -----------------------
    if (t == "ConditionalExpr" && n.children.size() == 3) {
      auto ast = enode(t);
      auto condition = enode("Condition");
      condition->children.push_back(
          extract(*n.children[0], ctx).first);
      ast->children.push_back(std::move(condition));
      ast->children.push_back(extract(*n.children[1], ctx).first);
      ast->children.push_back(extract(*n.children[2], ctx).first);
      return {std::move(ast), ctx};
    }

    // ---- switch entry: pattern bindings are arm-scoped ----------------
    // a 'case Type t ->' binding must not leak into sibling arms or past
    // the switch (it would capture same-named fields there). Ordinary
    // declarations still flow across classic ':' entries, matching the
    // reference's statement-group scoping (SwitchEntryStmt is not a
    // cell6 scope closer).
    if (t == "SwitchEntryStmt") {
      auto [children, final_ctx] = eval_children(n, ctx);
      std::vector<const Binding*> kept;
      for (const Binding* b = final_ctx.get(); b != ctx.get();
           b = b->next.get())
        if (!b->from_pattern) kept.push_back(b);
      Ctx out = ctx;
      for (auto it = kept.rbegin(); it != kept.rend(); ++it)
        out = std::make_shared<const Binding>(
            Binding{(*it)->space, (*it)->name, (*it)->id, out, false});
      auto ast = enode(t);
      ast->children = std::move(children);
      return {std::move(ast), out};
    }

    // ---- scope-closing containers (cell6) -----------------------------
    if (kScopeClosers.count(t)) {
      auto [children, _] = eval_children(n, ctx);
      auto ast = enode(t);
      ast->children = std::move(children);
      return {std::move(ast), ctx};  // close scope
    }

    // ---- default case (cell6) -----------------------------------------
    auto [children, new_ctx] = eval_children(n, ctx);
    if (n.leaf()) {
      if (kExpressionKinds.count(t) || kNameKinds.count(t) ||
          kTypeKinds.count(t) || t == "ArrayCreationLevel") {
        return {enode_terminal(t, node_source(n)), new_ctx};
      }
      if (kLeafStatementKinds.count(t)) {
        auto ast = enode(t);
        return {std::move(ast), new_ctx};
      }
      throw std::runtime_error("unhandled empty node: " + t);
    }
    auto ast = enode(t);
    ast->children = std::move(children);
    return {std::move(ast), new_ctx};
  }
};

// ---- terminal discovery (cell8 `findTerminal`) -------------------------
// Vocab-free: records the lowercased terminal name (what terminal_index
// would intern) instead of interning, so discovery can run off-thread.
struct TerminalEntry {
  const ENode* node;
  std::vector<std::pair<const ENode*, int>> path_from_root;
  int name_index;  // into MethodFeaturesStr::terminal_names
};

void find_terminals(const ENode& ast,
                    std::vector<std::pair<const ENode*, int>>& path,
                    std::vector<std::string>& terminal_names,
                    std::vector<TerminalEntry>& out) {
  if (ast.terminal.has_value()) {
    int idx = static_cast<int>(terminal_names.size());
    terminal_names.push_back(lower(*ast.terminal));  // vocab-size reduction
                                                     // (cell7), worker-side
    out.push_back({&ast, path, idx});
    return;
  }
  for (size_t i = 0; i < ast.children.size(); ++i) {
    path.emplace_back(ast.children[i].get(), static_cast<int>(i));
    find_terminals(*ast.children[i], path, terminal_names, out);
    path.pop_back();
  }
}

// ---- path computation (cell9 `getPath`) --------------------------------
// Path string uses the reference's UTF-8 arrows.
const char* kUp = "↑";    // ↑
const char* kDown = "↓";  // ↓

std::string get_path(const std::vector<std::pair<const ENode*, int>>& a,
                     const std::vector<std::pair<const ENode*, int>>& b,
                     int max_length, int max_width) {
  // strip common prefix; paths start with the shared root
  size_t i = 1;  // index 0 is the root in both
  const ENode* hinge = a[0].first;
  while (i < a.size() && i < b.size() && a[i].first == b[i].first) {
    hinge = a[i].first;
    ++i;
  }
  // both must have a distinct remainder (two different terminals)
  int width = a[i].second - b[i].second;
  if (width > max_width || -width > max_width) return "";
  size_t up_len = a.size() - i, down_len = b.size() - i;
  if (static_cast<int>(up_len + down_len + 1) > max_length) return "";

  std::string out;
  for (size_t k = a.size(); k-- > i;) {  // terminal-side, reversed
    out += a[k].first->name;
    out += kUp;
  }
  out += hinge->name;
  out += kDown;
  for (size_t k = i; k < b.size() - 1; ++k) {
    out += b[k].first->name;
    out += kDown;
  }
  out += b.back().first->name;  // last node, no arrow (cell9 Direction.Last)
  return out;
}

void collect_methods(const JNode& n, std::vector<const JNode*>& out) {
  if (n.type == "MethodDeclaration") out.push_back(&n);
  for (const auto& c : n.children) collect_methods(*c, out);
}

}  // namespace

Variable Env::fresh(const std::string& original) {
  Variable v{"@" + space + "_" + std::to_string(next_index), original};
  ++next_index;
  variables.push_back(v);
  return v;
}

int Vocabs::terminal_index(const std::string& terminal) {
  return terminal_index_lowered(lower(terminal));  // vocab-size reduction
                                                   // (cell7)
}

int Vocabs::terminal_index_lowered(const std::string& name) {
  auto it = terminal_map_.find(name);
  if (it != terminal_map_.end()) return it->second;
  int index = static_cast<int>(terminal_list_.size()) + 1;
  terminal_map_[name] = index;
  terminal_list_.emplace_back(name, index);
  return index;
}

int Vocabs::path_index(const std::string& path) {
  auto it = path_map_.find(path);
  if (it != path_map_.end()) return it->second;
  int index = static_cast<int>(path_list_.size()) + 1;
  path_map_[path] = index;
  path_list_.emplace_back(path, index);
  return index;
}

bool is_ignorable_method(const JNode& method) {
  const JNode* name_node = find_child(method, "SimpleName");
  std::string name = name_node ? name_node->text : "";
  const JNode* body = find_child(method, "BlockStmt");
  if (body == nullptr) return true;  // abstract
  if (kObjectMethods.count(name)) return true;
  if (name.rfind("set", 0) == 0) {
    if (count_children(method, "Parameter") == 1 &&
        body->children.size() == 1 &&
        body->children[0]->type == "ExpressionStmt" &&
        !body->children[0]->children.empty() &&
        body->children[0]->children[0]->type == "AssignExpr")
      return true;
    return false;
  }
  if (name.rfind("get", 0) == 0 || name.rfind("is", 0) == 0) {
    return count_children(method, "Parameter") == 0 &&
           body->children.size() == 1 &&
           body->children[0]->type == "ReturnStmt";
  }
  return false;
}

ENodePtr extract_ast(const JNode& method, VarEnv& env,
                     const ExtractConfig& config) {
  Extractor extractor{env, config};
  return extractor.extract(method, nullptr).first;
}

std::vector<MethodFeaturesStr> extract_features_str(
    const JNode& cu, const std::string& method_name,
    const ExtractConfig& config) {
  std::string target = lower(method_name);
  std::vector<const JNode*> methods;
  collect_methods(cu, methods);

  std::vector<MethodFeaturesStr> out;
  for (const JNode* m : methods) {
    const JNode* name_node = find_child(*m, "SimpleName");
    std::string name = name_node ? name_node->text : "";
    if (!(method_name == "*" || lower(name) == target)) continue;
    if (is_ignorable_method(*m)) continue;

    MethodFeaturesStr mf;
    mf.method_name = name;
    mf.method_source = m->text;
    ENodePtr ast = extract_ast(*m, mf.env, config);

    std::vector<TerminalEntry> terminals;
    std::vector<std::pair<const ENode*, int>> path{{ast.get(), 0}};
    find_terminals(*ast, path, mf.terminal_names, terminals);

    for (size_t i = 0; i < terminals.size(); ++i) {
      for (size_t j = i + 1; j < terminals.size(); ++j) {
        std::string p =
            get_path(terminals[i].path_from_root, terminals[j].path_from_root,
                     config.max_length, config.max_width);
        if (!p.empty()) {
          mf.features.push_back({terminals[i].name_index,
                                 terminals[j].name_index, std::move(p)});
        }
      }
    }
    out.push_back(std::move(mf));
  }
  return out;
}

MethodFeatures intern_features(MethodFeaturesStr mf, Vocabs& vocabs) {
  // Replays the sequential interning order exactly: every discovered
  // terminal in encounter order (even ones no surviving path touches —
  // find_terminals interned eagerly), then paths in (i, j) pair order.
  std::vector<int> ids;
  ids.reserve(mf.terminal_names.size());
  for (const auto& name : mf.terminal_names)
    ids.push_back(vocabs.terminal_index_lowered(name));
  MethodFeatures out;
  out.env = std::move(mf.env);
  out.method_name = std::move(mf.method_name);
  out.method_source = std::move(mf.method_source);
  out.features.reserve(mf.features.size());
  for (auto& f : mf.features)
    out.features.push_back(
        {ids[f.start_terminal], vocabs.path_index(f.path), ids[f.end_terminal]});
  return out;
}

std::vector<MethodFeatures> extract_features(const JNode& cu,
                                             const std::string& method_name,
                                             Vocabs& vocabs,
                                             const ExtractConfig& config) {
  std::vector<MethodFeatures> out;
  for (auto& mf : extract_features_str(cu, method_name, config))
    out.push_back(intern_features(std::move(mf), vocabs));
  return out;
}

}  // namespace c2v
