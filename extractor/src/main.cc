// c2v-extract: Java sources -> path-context corpus artifacts.
//
// CLI equivalent of the reference's createDataset (create_path_contexts
// .ipynb cell11): reads <dataset_dir>/methods.txt (TSV: java-file<TAB>
// method-name, method "*" = all), parses each file (compilation unit cached
// across consecutive rows of the same file), extracts features, and writes
// corpus.txt, terminal_idxs.txt, path_idxs.txt, params.txt,
// actual_methods.txt, and optionally method_declarations.txt.
//
// Parallel pipeline: consecutive same-file rows form a group (the unit the
// sequential CU cache covered); N workers parse+extract groups into
// vocab-free string features (extract_features_str), and the main thread
// commits results IN ROW ORDER, interning into the vocabs exactly as the
// sequential loop would — artifacts are byte-identical for any --jobs.
//
// Usage:
//   c2v-extract <dataset_dir> <source_dir> [options]
// Options:
//   --max-length N               path length cap (default 8)
//   --max-width N                sibling-width cap (default 3)
//   --jobs N                     worker threads (default: hardware cores)
//   --method-declarations FILE   also dump raw method sources
//   --no-normalize-string / --no-normalize-char
//   --normalize-int / --normalize-double

#include <atomic>
#include <condition_variable>
#include <fstream>
#include <iostream>
#include <map>
#include <memory>
#include <mutex>
#include <sstream>
#include <string>
#include <thread>
#include <vector>

#include "extract.h"
#include "parser.h"

namespace {

std::string read_file(const std::string& path) {
  std::ifstream f(path, std::ios::binary);
  if (!f) throw std::runtime_error("cannot open " + path);
  std::ostringstream ss;
  ss << f.rdbuf();
  return ss.str();
}

struct Row {
  std::string line;         // original methods.txt row (error messages)
  std::string method_name;  // after the TAB
};

struct RowOut {
  // Mirrors the sequential loop's three outcomes per row:
  //   0 = extracted; 1 = ParseError/LexError ("ERROR: parse error.");
  //   2 = other std::exception ("WARNING: <what>")
  int status = 0;
  std::string error_msg;
  std::vector<c2v::MethodFeaturesStr> features;
};

struct Group {
  std::string file;
  std::vector<Row> rows;
  std::vector<RowOut> outs;
  bool done = false;  // guarded by the pipeline mutex
};

// Streams methods.txt into consecutive same-file groups, one at a time —
// memory stays bounded by the in-flight window, not the corpus
// (java-large's methods.txt alone is ~16M rows). Groups are additionally
// capped at kMaxRowsPerGroup rows so one pathological same-file run (a
// generated file queried per-method) can't make a single group's
// rows+outs unbounded; splitting a run is safe because parsing is
// deterministic — each sub-group re-parses to the identical CU, and the
// committer preserves row order across sub-groups.
class GroupReader {
 public:
  static constexpr size_t kMaxRowsPerGroup = 4096;

  explicit GroupReader(std::istream& in) : in_(in) {}

  bool next(Group& g) {
    if (!has_pending_ && !read_row()) return false;
    g.file = pending_file_;
    g.rows.push_back(std::move(pending_row_));
    has_pending_ = false;
    while (g.rows.size() < kMaxRowsPerGroup && read_row()) {
      if (pending_file_ != g.file) return true;  // stays pending
      g.rows.push_back(std::move(pending_row_));
      has_pending_ = false;
    }
    return true;
  }

 private:
  bool read_row() {
    if (has_pending_) return true;
    std::string line;
    while (std::getline(in_, line)) {
      while (!line.empty() && (line.back() == '\r' || line.back() == '\n'))
        line.pop_back();
      if (line.empty()) continue;
      size_t tab = line.find('\t');
      if (tab == std::string::npos) continue;
      pending_file_ = line.substr(0, tab);
      pending_row_ = {line, line.substr(tab + 1)};
      has_pending_ = true;
      return true;
    }
    return false;
  }

  std::istream& in_;
  std::string pending_file_;
  Row pending_row_;
  bool has_pending_ = false;
};

// The sequential loop re-parses on every row after an error (it clears its
// CU cache), and parsing is deterministic — so one failed parse stands for
// the whole group, replicated per row.
void process_group(Group& g, const std::string& source_dir,
                   const c2v::ExtractConfig& config) {
  g.outs.resize(g.rows.size());
  c2v::JNodePtr cu;
  int parse_status = 0;
  std::string parse_msg;
  try {
    cu = c2v::parse_compilation_unit(read_file(source_dir + "/" + g.file));
  } catch (const c2v::ParseError& e) {
    parse_status = 1;
    parse_msg = e.what();
  } catch (const c2v::LexError& e) {
    // same actionable ERROR-with-row form as ParseError (which file to
    // exclude), e.g. the Java 15 text-block rejection
    parse_status = 1;
    parse_msg = e.what();
  } catch (const std::exception& e) {
    parse_status = 2;
    parse_msg = e.what();
  }
  for (size_t i = 0; i < g.rows.size(); ++i) {
    RowOut& out = g.outs[i];
    if (parse_status != 0) {
      out.status = parse_status;
      out.error_msg = parse_msg;
      continue;
    }
    try {
      out.features =
          c2v::extract_features_str(*cu, g.rows[i].method_name, config);
    } catch (const std::exception& e) {
      out.status = 2;
      out.error_msg = e.what();
    }
  }
}

}  // namespace

int main(int argc, char** argv) {
  if (argc < 3) {
    std::cerr << "usage: c2v-extract <dataset_dir> <source_dir> [options]\n";
    return 2;
  }
  std::string dataset_dir = argv[1];
  std::string source_dir = argv[2];
  c2v::ExtractConfig config;
  std::string method_declarations_name;
  int jobs = 0;  // 0 = hardware concurrency
  for (int i = 3; i < argc; ++i) {
    std::string arg = argv[i];
    if (arg == "--max-length" && i + 1 < argc) config.max_length = std::stoi(argv[++i]);
    else if (arg == "--max-width" && i + 1 < argc) config.max_width = std::stoi(argv[++i]);
    else if (arg == "--jobs" && i + 1 < argc) jobs = std::stoi(argv[++i]);
    else if (arg == "--method-declarations" && i + 1 < argc) method_declarations_name = argv[++i];
    else if (arg == "--no-normalize-string") config.normalize_string_literal = false;
    else if (arg == "--no-normalize-char") config.normalize_char_literal = false;
    else if (arg == "--normalize-int") config.normalize_int_literal = true;
    else if (arg == "--normalize-double") config.normalize_double_literal = true;
    else if (arg == "--no-normalize-double") config.normalize_double_literal = false;
    else {
      std::cerr << "unknown option: " << arg << "\n";
      return 2;
    }
  }
  if (jobs <= 0) {
    jobs = static_cast<int>(std::thread::hardware_concurrency());
    if (jobs <= 0) jobs = 1;
  }

  std::ifstream method_list(dataset_dir + "/methods.txt");
  if (!method_list) {
    std::cerr << "ERROR: cannot open " << dataset_dir << "/methods.txt\n";
    return 1;
  }

  std::ofstream corpus(dataset_dir + "/corpus.txt");
  std::ofstream actual_methods(dataset_dir + "/actual_methods.txt");
  std::ofstream method_declarations;
  if (!method_declarations_name.empty())
    method_declarations.open(dataset_dir + "/" + method_declarations_name);

  c2v::Vocabs vocabs;
  std::map<std::string, int> method_names;  // method_name_vocab_count
  int id_counter = 0;

  // ---- lazy producer + workers + in-order committer -------------------
  // A ring of `window` in-flight groups bounds memory to the window, not
  // the corpus: the main thread produces group idx only once the commit
  // frontier has passed idx - window, workers claim produced groups by
  // global index, and the main thread commits them back in order.
  const size_t window = static_cast<size_t>(jobs) * 4 + 16;
  std::mutex mu;
  std::condition_variable cv;
  std::vector<std::unique_ptr<Group>> ring(window);
  size_t produced = 0;  // guarded by mu
  bool eof = false;     // guarded by mu
  std::atomic<size_t> next_claim{0};

  auto worker = [&]() {
    for (;;) {
      size_t idx = next_claim.fetch_add(1);
      Group* g = nullptr;
      {
        std::unique_lock<std::mutex> lock(mu);
        cv.wait(lock, [&] { return idx < produced || eof; });
        if (idx >= produced) return;  // eof: no group idx will ever exist
        // the slot cannot be recycled while idx is uncommitted (the
        // producer stays within committed + window)
        g = ring[idx % window].get();
      }
      process_group(*g, source_dir, config);
      {
        std::lock_guard<std::mutex> lock(mu);
        g->done = true;
      }
      cv.notify_all();
    }
  };

  std::vector<std::thread> pool;
  for (int t = 0; t < jobs; ++t) pool.emplace_back(worker);

  auto commit_row = [&](Group& g, size_t i) {
    const Row& row = g.rows[i];
    RowOut& out = g.outs[i];
    if (out.status == 1) {
      std::cerr << "ERROR: parse error. " << row.line << " (" << out.error_msg
                << ")\n";
      return;
    }
    if (out.status == 2) {
      std::cerr << "WARNING: " << out.error_msg << "\n";
      return;
    }
    bool had_features = !out.features.empty();
    for (auto& mfs : out.features) {
      c2v::MethodFeatures mf = c2v::intern_features(std::move(mfs), vocabs);
      int corpus_id = id_counter++;
      corpus << "#" << corpus_id << "\n";
      corpus << "label:" << mf.method_name << "\n";
      corpus << "class:" << g.file << "\n";
      corpus << "paths:\n";
      for (const auto& f : mf.features)
        corpus << f.start << "\t" << f.path << "\t" << f.end << "\n";
      corpus << "vars:\n";
      // reverse encounter order (the reference's prepend-built lists)
      for (auto it = mf.env.vars.variables.rbegin();
           it != mf.env.vars.variables.rend(); ++it)
        corpus << it->name << "\t" << it->id << "\n";
      for (auto it = mf.env.labels.variables.rbegin();
           it != mf.env.labels.variables.rend(); ++it)
        corpus << it->name << "\t" << it->id << "\n";
      corpus << "\n";

      actual_methods << g.file << "\t" << mf.method_name << "\t" << corpus_id
                     << "\t" << mf.features.size() << "\n";
      if (method_declarations.is_open())
        method_declarations << "#" << corpus_id << "\t" << g.file << "#"
                            << mf.method_name << "\n"
                            << mf.method_source << "\n\n";
      ++method_names[mf.method_name];
    }
    if (!had_features && row.method_name != "*")
      std::cerr << "WARNING: method not found. " << row.line << "\n";
  };

  GroupReader reader(method_list);
  for (size_t commit_idx = 0;; ++commit_idx) {
    Group* g = nullptr;
    {
      std::unique_lock<std::mutex> lock(mu);
      // top up the window before sleeping, so every group a worker may
      // claim (all < committed + window) exists
      while (!eof && produced < commit_idx + window) {
        auto fresh = std::make_unique<Group>();
        if (reader.next(*fresh)) {
          ring[produced % window] = std::move(fresh);
          ++produced;
        } else {
          eof = true;
        }
        cv.notify_all();
      }
      if (commit_idx >= produced) break;  // eof and fully drained
      cv.wait(lock, [&] { return ring[commit_idx % window]->done; });
      g = ring[commit_idx % window].get();
    }
    for (size_t i = 0; i < g->rows.size(); ++i) commit_row(*g, i);
    {
      std::lock_guard<std::mutex> lock(mu);
      ring[commit_idx % window].reset();  // frees rows + features
    }
    cv.notify_all();
  }
  for (auto& t : pool) t.join();

  {
    std::ofstream terminal_idx(dataset_dir + "/terminal_idxs.txt");
    terminal_idx << "0\t<PAD/>\n";
    for (const auto& [name, index] : vocabs.terminals())
      terminal_idx << index << "\t" << name << "\n";
  }
  {
    std::ofstream path_idx(dataset_dir + "/path_idxs.txt");
    path_idx << "0\t<PAD/>\n";
    for (const auto& [name, index] : vocabs.paths())
      path_idx << index << "\t" << name << "\n";
  }
  {
    std::ofstream params(dataset_dir + "/params.txt");
    params << "max_length:" << config.max_length << "\n"
           << "max_width:" << config.max_width << "\n"
           << "nomalize_string_literal:" << (config.normalize_string_literal ? "true" : "false") << "\n"
           << "nomalize_char_literal:" << (config.normalize_char_literal ? "true" : "false") << "\n"
           << "nomalize_int_literal:" << (config.normalize_int_literal ? "true" : "false") << "\n"
           << "nomalize_double_literal:" << (config.normalize_double_literal ? "true" : "false") << "\n"
           << "terminal_vocab_count:" << vocabs.terminals().size() << "\n"
           << "path_vocab_count:" << vocabs.paths().size() << "\n"
           << "method_count:" << id_counter << "\n"
           << "method_name_vocab_count:" << method_names.size() << "\n";
  }
  std::cerr << "extracted " << id_counter << " methods, "
            << vocabs.terminals().size() << " terminals, "
            << vocabs.paths().size() << " paths\n";
  return 0;
}
