// c2v-extract: Java sources -> path-context corpus artifacts.
//
// CLI equivalent of the reference's createDataset (create_path_contexts
// .ipynb cell11): reads <dataset_dir>/methods.txt (TSV: java-file<TAB>
// method-name, method "*" = all), parses each file (compilation unit cached
// across consecutive rows of the same file), extracts features, and writes
// corpus.txt, terminal_idxs.txt, path_idxs.txt, params.txt,
// actual_methods.txt, and optionally method_declarations.txt.
//
// Usage:
//   c2v-extract <dataset_dir> <source_dir> [options]
// Options:
//   --max-length N               path length cap (default 8)
//   --max-width N                sibling-width cap (default 3)
//   --method-declarations FILE   also dump raw method sources
//   --no-normalize-string / --no-normalize-char
//   --normalize-int / --normalize-double

#include <fstream>
#include <iostream>
#include <map>
#include <sstream>
#include <string>
#include <vector>

#include "extract.h"
#include "parser.h"

namespace {

std::string read_file(const std::string& path) {
  std::ifstream f(path, std::ios::binary);
  if (!f) throw std::runtime_error("cannot open " + path);
  std::ostringstream ss;
  ss << f.rdbuf();
  return ss.str();
}

}  // namespace

int main(int argc, char** argv) {
  if (argc < 3) {
    std::cerr << "usage: c2v-extract <dataset_dir> <source_dir> [options]\n";
    return 2;
  }
  std::string dataset_dir = argv[1];
  std::string source_dir = argv[2];
  c2v::ExtractConfig config;
  std::string method_declarations_name;
  for (int i = 3; i < argc; ++i) {
    std::string arg = argv[i];
    if (arg == "--max-length" && i + 1 < argc) config.max_length = std::stoi(argv[++i]);
    else if (arg == "--max-width" && i + 1 < argc) config.max_width = std::stoi(argv[++i]);
    else if (arg == "--method-declarations" && i + 1 < argc) method_declarations_name = argv[++i];
    else if (arg == "--no-normalize-string") config.normalize_string_literal = false;
    else if (arg == "--no-normalize-char") config.normalize_char_literal = false;
    else if (arg == "--normalize-int") config.normalize_int_literal = true;
    else if (arg == "--normalize-double") config.normalize_double_literal = true;
    else if (arg == "--no-normalize-double") config.normalize_double_literal = false;
    else {
      std::cerr << "unknown option: " << arg << "\n";
      return 2;
    }
  }

  std::ifstream method_list(dataset_dir + "/methods.txt");
  if (!method_list) {
    std::cerr << "ERROR: cannot open " << dataset_dir << "/methods.txt\n";
    return 1;
  }

  std::ofstream corpus(dataset_dir + "/corpus.txt");
  std::ofstream actual_methods(dataset_dir + "/actual_methods.txt");
  std::ofstream method_declarations;
  if (!method_declarations_name.empty())
    method_declarations.open(dataset_dir + "/" + method_declarations_name);

  c2v::Vocabs vocabs;
  std::map<std::string, int> method_names;  // method_name_vocab_count
  int id_counter = 0;

  std::string last_file;
  c2v::JNodePtr last_cu;
  std::string line;
  while (std::getline(method_list, line)) {
    while (!line.empty() && (line.back() == '\r' || line.back() == '\n'))
      line.pop_back();
    if (line.empty()) continue;
    size_t tab = line.find('\t');
    if (tab == std::string::npos) continue;
    std::string java_file = line.substr(0, tab);
    std::string method_name = line.substr(tab + 1);

    try {
      if (java_file != last_file) {
        last_cu = c2v::parse_compilation_unit(
            read_file(source_dir + "/" + java_file));
        last_file = java_file;
      }
      auto features =
          c2v::extract_features(*last_cu, method_name, vocabs, config);
      for (auto& mf : features) {
        int corpus_id = id_counter++;
        corpus << "#" << corpus_id << "\n";
        corpus << "label:" << mf.method_name << "\n";
        corpus << "class:" << java_file << "\n";
        corpus << "paths:\n";
        for (const auto& f : mf.features)
          corpus << f.start << "\t" << f.path << "\t" << f.end << "\n";
        corpus << "vars:\n";
        // reverse encounter order (the reference's prepend-built lists)
        for (auto it = mf.env.vars.variables.rbegin();
             it != mf.env.vars.variables.rend(); ++it)
          corpus << it->name << "\t" << it->id << "\n";
        for (auto it = mf.env.labels.variables.rbegin();
             it != mf.env.labels.variables.rend(); ++it)
          corpus << it->name << "\t" << it->id << "\n";
        corpus << "\n";

        actual_methods << java_file << "\t" << mf.method_name << "\t"
                       << corpus_id << "\t" << mf.features.size() << "\n";
        if (method_declarations.is_open())
          method_declarations << "#" << corpus_id << "\t" << java_file << "#"
                              << mf.method_name << "\n"
                              << mf.method_source << "\n\n";
        ++method_names[mf.method_name];
      }
      if (features.empty() && method_name != "*")
        std::cerr << "WARNING: method not found. " << line << "\n";
    } catch (const c2v::ParseError& e) {
      std::cerr << "ERROR: parse error. " << line << " (" << e.what() << ")\n";
      last_file.clear();  // do not reuse a broken unit
    } catch (const c2v::LexError& e) {
      // same actionable ERROR-with-row form as ParseError (which file to
      // exclude), e.g. the Java 15 text-block rejection
      std::cerr << "ERROR: parse error. " << line << " (" << e.what() << ")\n";
      last_file.clear();
    } catch (const std::exception& e) {
      std::cerr << "WARNING: " << e.what() << "\n";
      last_file.clear();
    }
  }

  {
    std::ofstream terminal_idx(dataset_dir + "/terminal_idxs.txt");
    terminal_idx << "0\t<PAD/>\n";
    for (const auto& [name, index] : vocabs.terminals())
      terminal_idx << index << "\t" << name << "\n";
  }
  {
    std::ofstream path_idx(dataset_dir + "/path_idxs.txt");
    path_idx << "0\t<PAD/>\n";
    for (const auto& [name, index] : vocabs.paths())
      path_idx << index << "\t" << name << "\n";
  }
  {
    std::ofstream params(dataset_dir + "/params.txt");
    params << "max_length:" << config.max_length << "\n"
           << "max_width:" << config.max_width << "\n"
           << "nomalize_string_literal:" << (config.normalize_string_literal ? "true" : "false") << "\n"
           << "nomalize_char_literal:" << (config.normalize_char_literal ? "true" : "false") << "\n"
           << "nomalize_int_literal:" << (config.normalize_int_literal ? "true" : "false") << "\n"
           << "nomalize_double_literal:" << (config.normalize_double_literal ? "true" : "false") << "\n"
           << "terminal_vocab_count:" << vocabs.terminals().size() << "\n"
           << "path_vocab_count:" << vocabs.paths().size() << "\n"
           << "method_count:" << id_counter << "\n"
           << "method_name_vocab_count:" << method_names.size() << "\n";
  }
  std::cerr << "extracted " << id_counter << " methods, "
            << vocabs.terminals().size() << " terminals, "
            << vocabs.paths().size() << " paths\n";
  return 0;
}
