// Java lexer: comments stripped, string/char escapes handled, numeric
// literal classification (int/long/double incl. hex/binary/underscores),
// longest-match operators.

#pragma once

#include <stdexcept>
#include <string>
#include <vector>

namespace c2v {

// Unsupported lexical construct (e.g. Java 15 text blocks) — fails the
// file loudly with a construct-specific message, like the parser's
// ParseError does for unsupported grammar.
struct LexError : std::runtime_error {
  explicit LexError(const std::string& message) : std::runtime_error(message) {}
};

enum class Tok {
  kEnd,
  kIdent,      // identifiers and keywords (parser distinguishes)
  kInt,        // integer literal
  kLong,       // integer literal with l/L suffix
  kDouble,     // floating literal (also float 'f' suffix)
  kChar,       // 'c'
  kString,     // "..."
  kPunct,      // operators & punctuation, text holds the lexeme
};

struct Token {
  Tok kind = Tok::kEnd;
  std::string text;  // lexeme (for strings/chars: raw source incl. quotes)
  int line = 0;
  size_t begin = 0;  // source offsets (method_declarations.txt slicing)
  size_t end = 0;
};

class Lexer {
 public:
  explicit Lexer(const std::string& src);
  const std::vector<Token>& tokens() const { return tokens_; }

 private:
  void run(const std::string& src);
  std::vector<Token> tokens_;
};

}  // namespace c2v
