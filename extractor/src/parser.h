// Recursive-descent parser for a practical Java subset, producing
// javaparser-shaped ASTs (node-type names per javaparser 3.6, the library
// the reference notebook uses — create_path_contexts.ipynb cell1).
//
// Coverage: classes/interfaces/enums/annotations, fields, methods,
// constructors, initializer blocks, generics (incl. nested '>>' splitting),
// lambdas, method references, anonymous classes, arrays, the full
// statement/expression grammar with precedence, try-with-resources,
// multi-catch, labeled statements, switch.
//
// Out of scope (rejected with ParseError, reported as a parse warning by
// the dataset writer, matching the reference's swallow-and-warn behavior,
// ipynb cell11): records, sealed classes, pattern-matching switch, text
// blocks, modules.

#pragma once

#include <stdexcept>
#include <string>

#include "ast.h"
#include "lexer.h"

namespace c2v {

struct ParseError : std::runtime_error {
  explicit ParseError(const std::string& message) : std::runtime_error(message) {}
};

// Parse a whole source file into a CompilationUnit node.
JNodePtr parse_compilation_unit(const std::string& source);

}  // namespace c2v
