#include "parser.h"

#include <set>

namespace c2v {

namespace {

const std::set<std::string> kPrimitives = {
    "boolean", "byte", "char", "short", "int", "long", "float", "double"};

const std::set<std::string> kModifiers = {
    "public", "protected", "private", "static",   "final",    "abstract",
    "native", "synchronized", "transient", "volatile", "strictfp", "default"};

// javaparser operator enum names (BinaryExpr.Operator etc.)
std::string binary_op_name(const std::string& op) {
  if (op == "||") return "OR";
  if (op == "&&") return "AND";
  if (op == "|") return "BINARY_OR";
  if (op == "&") return "BINARY_AND";
  if (op == "^") return "XOR";
  if (op == "==") return "EQUALS";
  if (op == "!=") return "NOT_EQUALS";
  if (op == "<") return "LESS";
  if (op == ">") return "GREATER";
  if (op == "<=") return "LESS_EQUALS";
  if (op == ">=") return "GREATER_EQUALS";
  if (op == "<<") return "LEFT_SHIFT";
  if (op == ">>") return "SIGNED_RIGHT_SHIFT";
  if (op == ">>>") return "UNSIGNED_RIGHT_SHIFT";
  if (op == "+") return "PLUS";
  if (op == "-") return "MINUS";
  if (op == "*") return "MULTIPLY";
  if (op == "/") return "DIVIDE";
  if (op == "%") return "REMAINDER";
  return "UNKNOWN";
}

std::string assign_op_name(const std::string& op) {
  if (op == "=") return "ASSIGN";
  if (op == "+=") return "PLUS";
  if (op == "-=") return "MINUS";
  if (op == "*=") return "MULTIPLY";
  if (op == "/=") return "DIVIDE";
  if (op == "&=") return "AND";
  if (op == "|=") return "OR";
  if (op == "^=") return "XOR";
  if (op == "%=") return "REMAINDER";
  if (op == "<<=") return "LEFT_SHIFT";
  if (op == ">>=") return "SIGNED_RIGHT_SHIFT";
  if (op == ">>>=") return "UNSIGNED_RIGHT_SHIFT";
  return "UNKNOWN";
}

class Parser {
 public:
  Parser(const std::string& source)
      : source_(source), lexer_(source), toks_(lexer_.tokens()) {}

  JNodePtr run() {
    auto cu = make("CompilationUnit");
    if (at_ident("package")) {
      next();
      auto pd = make("PackageDeclaration");
      pd->add(parse_qualified_name());
      expect(";");
      cu->add(std::move(pd));
    }
    while (at_ident("import")) {
      next();
      auto im = make("ImportDeclaration");
      if (at_ident("static")) next();
      im->add(parse_qualified_name(/*allow_star=*/true));
      expect(";");
      cu->add(std::move(im));
    }
    while (!at_end()) {
      if (at(";")) { next(); continue; }
      cu->add(parse_type_declaration());
    }
    return cu;
  }

 private:
  // ---- token helpers -------------------------------------------------
  const Token& cur() const { return toks_[pos_]; }
  const Token& peek(int k = 1) const {
    size_t p = pos_ + k;
    return toks_[p < toks_.size() ? p : toks_.size() - 1];
  }
  bool at_end() const { return cur().kind == Tok::kEnd; }
  bool at(const std::string& p) const {
    return cur().kind == Tok::kPunct && cur().text == p;
  }
  bool at_ident(const std::string& name) const {
    return cur().kind == Tok::kIdent && cur().text == name;
  }
  bool at_offset_is(int k, const std::string& p) const {
    return peek(k).kind == Tok::kPunct && peek(k).text == p;
  }
  void next() { if (!at_end()) ++pos_; }
  void expect(const std::string& p) {
    if (!at(p)) fail("expected '" + p + "'");
    next();
  }
  std::string expect_ident() {
    if (cur().kind != Tok::kIdent) fail("expected identifier");
    std::string s = cur().text;
    next();
    return s;
  }
  [[noreturn]] void fail(const std::string& message) const {
    throw ParseError("line " + std::to_string(cur().line) + ": " + message +
                     " (got '" + cur().text + "')");
  }

  // '>' inside nested generics may be lexed as '>>'/'>>>'; split in place.
  void expect_close_angle() {
    if (at(">")) { next(); return; }
    if (cur().kind == Tok::kPunct &&
        (cur().text == ">>" || cur().text == ">>>" || cur().text == ">=")) {
      mutable_tok().text = cur().text.substr(1);
      return;
    }
    fail("expected '>'");
  }
  Token& mutable_tok() { return const_cast<Token&>(toks_[pos_]); }

  void skip_annotations_into(JNode* parent) {
    while (at("@") && peek().kind == Tok::kIdent &&
           !(peek().text == "interface")) {
      parent->add(parse_annotation());
    }
  }

  void skip_modifiers() {
    while (true) {
      if (cur().kind == Tok::kIdent && kModifiers.count(cur().text)) {
        next();
        continue;
      }
      // Java 17 'sealed' — contextual: a modifier only when a declaration
      // head follows, so a pre-17 class actually NAMED sealed ('sealed s;')
      // keeps its type reading (same lookahead discipline as var/record)
      if (at_ident("sealed") && peek().kind == Tok::kIdent &&
          (kModifiers.count(peek().text) || peek().text == "class" ||
           peek().text == "interface" || peek().text == "enum" ||
           peek().text == "non" ||
           (peek().text == "record" && peek(2).kind == Tok::kIdent))) {
        next();
        continue;
      }
      // Java 17 'non-sealed' lexes as Ident('non') '-' Ident('sealed')
      if (at_ident("non") && at_offset_is(1, "-") &&
          peek(2).kind == Tok::kIdent && peek(2).text == "sealed") {
        next(); next(); next();
        continue;
      }
      break;
    }
  }

  // ---- names & annotations -------------------------------------------
  JNodePtr parse_qualified_name(bool allow_star = false) {
    std::string name = expect_ident();
    while (at(".")) {
      if (allow_star && peek().kind == Tok::kPunct && peek().text == "*") {
        next();  // .
        next();  // *
        name += ".*";
        break;
      }
      next();
      name += "." + expect_ident();
    }
    return make("Name", name);
  }

  JNodePtr parse_annotation() {
    expect("@");
    auto name = parse_qualified_name();
    if (at("(")) {
      next();
      if (at(")")) {
        next();
        auto a = make("NormalAnnotationExpr");
        a->add(std::move(name));
        return a;
      }
      // key=value pairs or a single member value
      if (cur().kind == Tok::kIdent && peek().kind == Tok::kPunct &&
          peek().text == "=") {
        auto a = make("NormalAnnotationExpr");
        a->add(std::move(name));
        while (true) {
          auto pair = make("MemberValuePair");
          pair->add(make("SimpleName", expect_ident()));
          expect("=");
          pair->add(parse_member_value());
          a->add(std::move(pair));
          if (at(",")) { next(); continue; }
          break;
        }
        expect(")");
        return a;
      }
      auto a = make("SingleMemberAnnotationExpr");
      a->add(std::move(name));
      a->add(parse_member_value());
      expect(")");
      return a;
    }
    auto a = make("MarkerAnnotationExpr");
    a->add(std::move(name));
    return a;
  }

  JNodePtr parse_member_value() {
    if (at("{")) {  // array initializer inside annotation
      next();
      auto arr = make("ArrayInitializerExpr");
      while (!at("}")) {
        arr->add(parse_member_value());
        if (at(",")) next();
      }
      expect("}");
      return arr;
    }
    if (at("@")) return parse_annotation();
    return parse_expression();
  }

  // ---- types ----------------------------------------------------------
  bool looks_like_type_start() const {
    return cur().kind == Tok::kIdent &&
           (kPrimitives.count(cur().text) || cur().text == "void" ||
            (!kReservedNonType.count(cur().text)));
  }

  JNodePtr parse_type() {
    JNodePtr base;
    // Java 10 'var' (local-variable type inference): only when used where a
    // declared name follows, so a pre-Java-10 class actually NAMED var
    // ('var.foo()', 'new var()') still parses as a type name
    if (at_ident("var") && peek().kind == Tok::kIdent &&
        !kReservedNonType.count(peek().text)) {
      next();
      return make("VarType", "var");
    }
    if (cur().kind == Tok::kIdent && kPrimitives.count(cur().text)) {
      base = make("PrimitiveType", cur().text);
      next();
    } else if (at_ident("void")) {
      base = make("VoidType", "void");
      next();
    } else if (at("?")) {
      next();
      base = make("WildcardType", "?");
      if (at_ident("extends") || at_ident("super")) {
        next();
        base->add(parse_type());
      }
    } else {
      base = parse_class_type();
    }
    while (at("[")) {
      next();
      expect("]");
      auto arr = make("ArrayType");
      arr->add(std::move(base));
      base = std::move(arr);
    }
    return base;
  }

  JNodePtr parse_class_type() {
    auto t = make("ClassOrInterfaceType");
    t->add(make("SimpleName", expect_ident()));
    if (at("<")) parse_type_arguments_into(t.get());
    while (at(".") && peek().kind == Tok::kIdent) {
      next();
      auto outer = std::move(t);
      t = make("ClassOrInterfaceType");
      t->add(std::move(outer));  // scope
      t->add(make("SimpleName", expect_ident()));
      if (at("<")) parse_type_arguments_into(t.get());
    }
    return t;
  }

  void parse_type_arguments_into(JNode* t) {
    expect("<");
    if (at(">")) { next(); return; }  // diamond <>
    if (cur().kind == Tok::kPunct && cur().text.rfind(">", 0) == 0) {
      expect_close_angle();
      return;
    }
    while (true) {
      t->add(parse_type());
      if (at(",")) { next(); continue; }
      break;
    }
    expect_close_angle();
  }

  // heuristic: could the token sequence starting at pos_ be `(Type)` for a
  // cast, given what follows the ')'?
  bool looks_like_cast() const {
    size_t p = pos_ + 1;  // after '('
    int depth = 0;
    bool saw_ident = false;
    while (p < toks_.size()) {
      const Token& t = toks_[p];
      if (t.kind == Tok::kPunct) {
        if (t.text == "(") return false;
        if (t.text == ")" && depth == 0) break;
        if (t.text == "<") ++depth;
        else if (t.text == ">") --depth;
        else if (t.text == ">>") depth -= 2;
        else if (t.text == ">>>") depth -= 3;
        else if (t.text != "." && t.text != "[" && t.text != "]" &&
                 t.text != "," && t.text != "&" && t.text != "?")
          return false;
      } else if (t.kind == Tok::kIdent) {
        if (kReservedNonType.count(t.text) && !kPrimitives.count(t.text) &&
            t.text != "extends" && t.text != "super")
          return false;
        saw_ident = true;
      } else {
        return false;
      }
      ++p;
    }
    if (!saw_ident || p >= toks_.size()) return false;
    const Token& after = toks_[p + 1 < toks_.size() ? p + 1 : p];
    if (after.kind == Tok::kIdent)
      // any identifier or keyword expression-starter continues a cast —
      // including null/true/false ('(String) null') — except a binary-ish
      // keyword that can follow an EnclosedExpr
      return after.text != "instanceof";
    if (after.kind == Tok::kPunct)
      return after.text == "(" || after.text == "!" || after.text == "~";
    return after.kind == Tok::kInt || after.kind == Tok::kLong ||
           after.kind == Tok::kDouble || after.kind == Tok::kChar ||
           after.kind == Tok::kString;
  }

  // lambda lookahead: '(' ... ')' '->'
  bool looks_like_lambda_parens() const {
    size_t p = pos_ + 1;
    int depth = 1;
    while (p < toks_.size() && depth > 0) {
      const Token& t = toks_[p];
      if (t.kind == Tok::kPunct) {
        if (t.text == "(") ++depth;
        else if (t.text == ")") --depth;
      }
      ++p;
    }
    return p < toks_.size() && toks_[p].kind == Tok::kPunct &&
           toks_[p].text == "->";
  }

  // ---- type declarations ----------------------------------------------
  JNodePtr parse_type_declaration() {
    auto pending_annotations = make("__annotations__");
    while (at("@") && !(peek().kind == Tok::kIdent && peek().text == "interface"))
      pending_annotations->add(parse_annotation());
    skip_modifiers();
    while (at("@") && !(peek().kind == Tok::kIdent && peek().text == "interface"))
      pending_annotations->add(parse_annotation());
    skip_modifiers();

    if (at_ident("class") || at_ident("interface")) {
      bool is_interface = at_ident("interface");
      next();
      auto decl = make("ClassOrInterfaceDeclaration");
      for (auto& a : pending_annotations->children) decl->add(std::move(a));
      decl->add(make("SimpleName", expect_ident()));
      if (at("<")) parse_type_parameters_into(decl.get());
      if (at_ident("extends")) {
        next();
        decl->add(parse_class_type());
        while (at(",")) { next(); decl->add(parse_class_type()); }
      }
      if (at_ident("implements")) {
        next();
        decl->add(parse_class_type());
        while (at(",")) { next(); decl->add(parse_class_type()); }
      }
      if (at_ident("permits")) {
        // Java 17 permitted-subtype list: parsed but not kept — extraction
        // is per-method, and class-level children never enter a method's
        // AST, so recording them would only churn node shapes
        next();
        parse_class_type();
        while (at(",")) { next(); parse_class_type(); }
      }
      parse_class_body_into(decl.get(), is_interface);
      return decl;
    }
    if (at_ident("enum")) {
      next();
      auto decl = make("EnumDeclaration");
      for (auto& a : pending_annotations->children) decl->add(std::move(a));
      decl->add(make("SimpleName", expect_ident()));
      if (at_ident("implements")) {
        next();
        decl->add(parse_class_type());
        while (at(",")) { next(); decl->add(parse_class_type()); }
      }
      expect("{");
      while (cur().kind == Tok::kIdent || at("@")) {
        auto constant = make("EnumConstantDeclaration");
        while (at("@")) constant->add(parse_annotation());
        constant->add(make("SimpleName", expect_ident()));
        if (at("(")) parse_arguments_into(constant.get());
        if (at("{")) parse_class_body_into(constant.get(), false, /*already_open=*/false);
        if (at(",")) { next(); continue; }
        break;
      }
      if (at(";")) {
        next();
        while (!at("}")) parse_member_into(decl.get(), false);
      }
      expect("}");
      return decl;
    }
    if (at("@") && peek().kind == Tok::kIdent && peek().text == "interface") {
      next();  // @
      next();  // interface
      auto decl = make("AnnotationDeclaration");
      decl->add(make("SimpleName", expect_ident()));
      expect("{");
      while (!at("}")) {
        if (at(";")) { next(); continue; }
        skip_modifiers();
        auto member = make("AnnotationMemberDeclaration");
        member->add(parse_type());
        member->add(make("SimpleName", expect_ident()));
        expect("(");
        expect(")");
        if (at_ident("default")) { next(); member->add(parse_member_value()); }
        expect(";");
        decl->add(std::move(member));
      }
      expect("}");
      return decl;
    }
    // Java 16 record: components parse as Parameter nodes (javaparser's
    // RecordDeclaration shape); members extract like any class body
    if (at_ident("record") && peek().kind == Tok::kIdent) {
      next();
      auto decl = make("RecordDeclaration");
      for (auto& a : pending_annotations->children) decl->add(std::move(a));
      decl->add(make("SimpleName", expect_ident()));
      if (at("<")) parse_type_parameters_into(decl.get());
      parse_parameters_into(decl.get());
      if (at_ident("implements")) {
        next();
        decl->add(parse_class_type());
        while (at(",")) { next(); decl->add(parse_class_type()); }
      }
      parse_class_body_into(decl.get(), false);
      return decl;
    }
    fail("expected type declaration");
  }

  void parse_type_parameters_into(JNode* decl) {
    expect("<");
    while (true) {
      auto tp = make("TypeParameter");
      tp->add(make("SimpleName", expect_ident()));
      if (at_ident("extends")) {
        next();
        tp->add(parse_class_type());
        while (at("&")) { next(); tp->add(parse_class_type()); }
      }
      decl->add(std::move(tp));
      if (at(",")) { next(); continue; }
      break;
    }
    expect_close_angle();
  }

  void parse_class_body_into(JNode* decl, bool is_interface,
                             bool already_open = false) {
    if (!already_open) expect("{");
    while (!at("}")) {
      if (at(";")) { next(); continue; }
      parse_member_into(decl, is_interface);
    }
    expect("}");
  }

  void parse_member_into(JNode* decl, bool is_interface) {
    auto annotations = make("__annotations__");
    while (at("@") && !(peek().kind == Tok::kIdent && peek().text == "interface"))
      annotations->add(parse_annotation());
    skip_modifiers();
    while (at("@") && !(peek().kind == Tok::kIdent && peek().text == "interface"))
      annotations->add(parse_annotation());
    skip_modifiers();

    if (at_ident("class") || at_ident("interface") || at_ident("enum") ||
        at_record_decl() ||
        (at("@") && peek().kind == Tok::kIdent && peek().text == "interface")) {
      decl->add(parse_type_declaration());
      return;
    }
    if (at("{")) {  // instance/static initializer
      auto init = make("InitializerDeclaration");
      init->add(parse_block());
      decl->add(std::move(init));
      return;
    }

    size_t decl_begin = cur().begin;

    // record compact constructor: Ident '{' (no parameter list)
    if (cur().kind == Tok::kIdent && peek().kind == Tok::kPunct &&
        peek().text == "{" && !kPrimitives.count(cur().text)) {
      auto ctor = make("CompactConstructorDeclaration");
      for (auto& a : annotations->children) ctor->add(std::move(a));
      ctor->add(make("SimpleName", expect_ident()));
      ctor->add(parse_block());
      decl->add(std::move(ctor));
      return;
    }

    // constructor: Ident '(' with Ident == enclosing simple name shape
    auto type_params = make("__tps__");
    if (at("<")) parse_type_parameters_into(type_params.get());

    if (cur().kind == Tok::kIdent && peek().kind == Tok::kPunct &&
        peek().text == "(" && !kPrimitives.count(cur().text)) {
      auto ctor = make("ConstructorDeclaration");
      for (auto& a : annotations->children) ctor->add(std::move(a));
      for (auto& tp : type_params->children) ctor->add(std::move(tp));
      ctor->add(make("SimpleName", expect_ident()));
      parse_parameters_into(ctor.get());
      if (at_ident("throws")) {
        next();
        ctor->add(parse_class_type());
        while (at(",")) { next(); ctor->add(parse_class_type()); }
      }
      ctor->add(parse_block());
      decl->add(std::move(ctor));
      return;
    }

    auto return_type = parse_type();
    if (cur().kind != Tok::kIdent) fail("expected member name");
    std::string name = expect_ident();

    if (at("(")) {  // method
      auto method = make("MethodDeclaration");
      for (auto& a : annotations->children) method->add(std::move(a));
      for (auto& tp : type_params->children) method->add(std::move(tp));
      method->add(std::move(return_type));
      method->add(make("SimpleName", name));
      parse_parameters_into(method.get());
      while (at("[")) { next(); expect("]"); }  // legacy array-return syntax
      if (at_ident("throws")) {
        next();
        method->add(parse_class_type());
        while (at(",")) { next(); method->add(parse_class_type()); }
      }
      if (at(";")) {
        next();  // abstract/interface method: no body child
      } else if (at_ident("default") || at("{")) {
        method->add(parse_block());
      } else {
        fail("expected method body or ';'");
      }
      method->text = source_.substr(decl_begin, prev_end() - decl_begin);
      decl->add(std::move(method));
      (void)is_interface;
      return;
    }

    // field(s)
    auto field = make("FieldDeclaration");
    for (auto& a : annotations->children) field->add(std::move(a));
    field->add(
        parse_variable_declarators(std::move(return_type), name));
    while (at(",")) {
      next();
      std::string more = expect_ident();
      field->add(parse_variable_declarators(nullptr, more));
    }
    expect(";");
    decl->add(std::move(field));
  }

  size_t prev_end() const { return pos_ ? toks_[pos_ - 1].end : 0; }

  JNodePtr parse_variable_declarators(JNodePtr type, const std::string& name) {
    auto declarator = make("VariableDeclarator");
    declarator->add(make("SimpleName", name));
    JNodePtr t = std::move(type);
    while (at("[")) { next(); expect("]");
      auto arr = make("ArrayType");
      if (t) arr->add(std::move(t));
      t = std::move(arr);
    }
    if (t) declarator->add(std::move(t));
    if (at("=")) {
      next();
      declarator->add(parse_variable_initializer());
    }
    return declarator;
  }

  JNodePtr parse_variable_initializer() {
    if (at("{")) {
      next();
      auto arr = make("ArrayInitializerExpr");
      while (!at("}")) {
        arr->add(parse_variable_initializer());
        if (at(",")) next();
      }
      expect("}");
      return arr;
    }
    return parse_expression();
  }

  void parse_parameters_into(JNode* owner) {
    expect("(");
    while (!at(")")) {
      auto param = make("Parameter");
      while (at("@")) param->add(parse_annotation());
      if (at_ident("final")) next();
      while (at("@")) param->add(parse_annotation());
      // bare lambda-style params have no type; method params always do
      auto type = parse_type();
      bool varargs = false;
      if (at(".")) {  // '...' lexed as three '.' puncts
        next(); expect("."); expect(".");
        varargs = true;
      }
      param->is_var_args = varargs;
      param->add(std::move(type));
      param->add(make("SimpleName", expect_ident()));
      while (at("[")) { next(); expect("]"); }
      owner->add(std::move(param));
      if (at(",")) next();
    }
    expect(")");
  }

  // ---- statements ------------------------------------------------------
  JNodePtr parse_block() {
    expect("{");
    auto block = make("BlockStmt");
    while (!at("}")) block->add(parse_statement());
    expect("}");
    return block;
  }

  JNodePtr parse_statement() {
    if (at("{")) return parse_block();
    if (at(";")) { next(); return make("EmptyStmt"); }
    if ((at_ident("this") || at_ident("super")) &&
        peek().kind == Tok::kPunct && peek().text == "(") {
      // constructor chaining: this(...) / super(...)
      auto s = make("ExplicitConstructorInvocationStmt");
      s->text = cur().text;  // which form was chained
      next();
      parse_arguments_into(s.get());
      expect(";");
      return s;
    }
    if (at_ident("if")) {
      next();
      auto s = make("IfStmt");
      expect("(");
      s->add(parse_expression());
      expect(")");
      s->add(parse_statement());
      if (at_ident("else")) { next(); s->add(parse_statement()); }
      return s;
    }
    if (at_ident("while")) {
      next();
      auto s = make("WhileStmt");
      expect("(");
      s->add(parse_expression());
      expect(")");
      s->add(parse_statement());
      return s;
    }
    if (at_ident("do")) {
      next();
      auto s = make("DoStmt");
      s->add(parse_statement());
      if (!at_ident("while")) fail("expected 'while'");
      next();
      expect("(");
      s->add(parse_expression());
      expect(")");
      expect(";");
      return s;
    }
    if (at_ident("for")) return parse_for();
    if (at_ident("return")) {
      next();
      auto s = make("ReturnStmt");
      if (!at(";")) s->add(parse_expression());
      expect(";");
      return s;
    }
    if (at_ident("throw")) {
      next();
      auto s = make("ThrowStmt");
      s->add(parse_expression());
      expect(";");
      return s;
    }
    if (at_ident("break")) {
      next();
      auto s = make("BreakStmt");
      if (cur().kind == Tok::kIdent) s->add(make("SimpleName", expect_ident()));
      expect(";");
      return s;
    }
    if (at_ident("continue")) {
      next();
      auto s = make("ContinueStmt");
      if (cur().kind == Tok::kIdent) s->add(make("SimpleName", expect_ident()));
      expect(";");
      return s;
    }
    if (at_ident("switch")) return parse_switch(/*as_expr=*/false);
    if (at_ident("try")) return parse_try();
    // Java 14 'yield expr;' — contextual keyword, only live inside a
    // switch *expression* body (switch_expr_depth_): there JLS 14.8
    // forbids an expression statement from starting with 'yield', so any
    // expression-starter after it — including '(', '++', '--' — reads as a
    // yield. Outside, pre-14 code using yield as a method/variable name
    // ('yield();', 'yield = 1;') keeps its expression reading. (Known
    // approximation: a lambda body nested in a switch expression
    // re-enables the expression reading in real Java; not tracked.)
    if (switch_expr_depth_ > 0 && at_ident("yield") &&
        !(peek().kind == Tok::kPunct &&
          (peek().text == ";" || peek().text == "=" || peek().text == "." ||
           peek().text == "[" || peek().text == "::"))) {
      next();
      auto s = make("YieldStmt");
      s->add(parse_expression());
      expect(";");
      return s;
    }
    if (at_ident("synchronized") && peek().kind == Tok::kPunct && peek().text == "(") {
      next();
      auto s = make("SynchronizedStmt");
      expect("(");
      s->add(parse_expression());
      expect(")");
      s->add(parse_block());
      return s;
    }
    if (at_ident("assert")) {
      next();
      auto s = make("AssertStmt");
      s->add(parse_expression());
      if (at(":")) { next(); s->add(parse_expression()); }
      expect(";");
      return s;
    }
    if (at_ident("class") || leads_to_local_class() || at_record_decl()) {
      auto s = make("LocalClassDeclarationStmt");
      s->add(parse_type_declaration());
      return s;
    }
    // annotated local variable declaration ('@SuppressWarnings(...) T x = ...')
    if (at("@") && !annotation_precedes_class()) {
      auto s = make("ExpressionStmt");
      s->add(parse_local_var_decl());
      expect(";");
      return s;
    }
    if (at("@")) {  // annotated local class
      auto s = make("LocalClassDeclarationStmt");
      s->add(parse_type_declaration());
      return s;
    }
    // labeled statement: Ident ':'
    if (cur().kind == Tok::kIdent && peek().kind == Tok::kPunct &&
        peek().text == ":" && !kReservedNonType.count(cur().text)) {
      auto s = make("LabeledStmt");
      s->add(make("SimpleName", expect_ident()));
      expect(":");
      s->add(parse_statement());
      return s;
    }
    // local variable declaration vs expression statement
    if (starts_local_var_decl()) {
      auto s = make("ExpressionStmt");
      s->add(parse_local_var_decl());
      expect(";");
      return s;
    }
    auto s = make("ExpressionStmt");
    s->add(parse_expression());
    expect(";");
    return s;
  }

  // 'record Ident (' / 'record Ident <' is a record declaration, not an
  // identifier that happens to be named record
  bool at_record_decl() const {
    return cur().kind == Tok::kIdent && cur().text == "record" &&
           peek().kind == Tok::kIdent &&
           (at_offset_is(2, "(") || at_offset_is(2, "<"));
  }

  // 'final'/'abstract'/'static' (possibly stacked) directly before 'class'
  // means a modifier-prefixed local class declaration
  bool leads_to_local_class() const {
    size_t p = pos_;
    while (p < toks_.size() && toks_[p].kind == Tok::kIdent &&
           (toks_[p].text == "final" || toks_[p].text == "abstract" ||
            toks_[p].text == "static"))
      ++p;
    return p > pos_ && p < toks_.size() && toks_[p].kind == Tok::kIdent &&
           toks_[p].text == "class";
  }

  // after leading annotations (and modifiers), is this a class declaration?
  bool annotation_precedes_class() const {
    size_t p = pos_;
    while (p < toks_.size() && toks_[p].kind == Tok::kPunct &&
           toks_[p].text == "@") {
      ++p;  // @
      if (p < toks_.size() && toks_[p].kind == Tok::kIdent) ++p;
      while (p < toks_.size() && toks_[p].kind == Tok::kPunct &&
             toks_[p].text == ".") {
        p += 2;  // .Ident
      }
      if (p < toks_.size() && toks_[p].kind == Tok::kPunct &&
          toks_[p].text == "(") {
        int depth = 1;
        ++p;
        while (p < toks_.size() && depth > 0) {
          if (toks_[p].kind == Tok::kPunct) {
            if (toks_[p].text == "(") ++depth;
            else if (toks_[p].text == ")") --depth;
          }
          ++p;
        }
      }
    }
    while (p < toks_.size() && toks_[p].kind == Tok::kIdent &&
           kModifiers.count(toks_[p].text))
      ++p;
    return p < toks_.size() && toks_[p].kind == Tok::kIdent &&
           (toks_[p].text == "class" || toks_[p].text == "interface" ||
            toks_[p].text == "enum");
  }

  bool starts_local_var_decl() {
    if (cur().kind != Tok::kIdent) return false;
    if (at_ident("final") || (kPrimitives.count(cur().text))) return true;
    if (kReservedNonType.count(cur().text)) return false;
    // Ident(.Ident)*(<...>)?([])* Ident  (=> declaration)
    size_t p = pos_;
    int angle = 0;
    bool seen_type = false;
    while (p < toks_.size()) {
      const Token& t = toks_[p];
      if (t.kind == Tok::kIdent) {
        if (kReservedNonType.count(t.text) && !kPrimitives.count(t.text) &&
            t.text != "extends" && t.text != "super")
          return false;
        if (seen_type && angle == 0) return true;  // second bare ident
        seen_type = true;
        ++p;
        continue;
      }
      if (t.kind != Tok::kPunct) return false;
      if (t.text == ".") {
        // '.<' is an explicit-type-argument call (Foo.<String>bar()), never
        // a declaration
        if (p + 1 < toks_.size() && toks_[p + 1].kind == Tok::kPunct &&
            toks_[p + 1].text == "<")
          return false;
        seen_type = false; ++p; continue;
      }
      if (t.text == "<") { ++angle; ++p; continue; }
      if (t.text == ">") { --angle; ++p; continue; }
      if (t.text == ">>") { angle -= 2; ++p; continue; }
      if (t.text == ">>>") { angle -= 3; ++p; continue; }
      if (t.text == "[") {
        if (p + 1 < toks_.size() && toks_[p + 1].kind == Tok::kPunct &&
            toks_[p + 1].text == "]") { p += 2; continue; }
        return false;
      }
      if (t.text == "," && angle > 0) { ++p; continue; }
      if (t.text == "?" && angle > 0) { ++p; continue; }
      return false;
    }
    return false;
  }

  JNodePtr parse_local_var_decl() {
    if (at_ident("final")) next();
    auto decl_expr = make("VariableDeclarationExpr");
    while (at("@")) decl_expr->add(parse_annotation());
    if (at_ident("final")) next();
    auto type = parse_type();
    std::string name = expect_ident();
    decl_expr->add(parse_variable_declarators(clone(type.get()), name));
    while (at(",")) {
      next();
      std::string more = expect_ident();
      decl_expr->add(parse_variable_declarators(clone(type.get()), more));
    }
    return decl_expr;
  }

  JNodePtr parse_for() {
    next();  // for
    expect("(");
    // enhanced for: [final] Type Ident ':'
    size_t save = pos_;
    bool enhanced = false;
    try {
      if (at_ident("final")) next();
      if (starts_local_var_decl() || kPrimitives.count(cur().text)) {
        auto probe_type = parse_type();
        (void)probe_type;
        if (cur().kind == Tok::kIdent && peek().kind == Tok::kPunct &&
            peek().text == ":")
          enhanced = true;
      }
    } catch (const ParseError&) {}
    pos_ = save;

    if (enhanced) {
      auto s = make("ForeachStmt");  // javaparser 3.6 name
      auto var = make("VariableDeclarationExpr");
      if (at_ident("final")) next();
      auto type = parse_type();
      auto declarator = make("VariableDeclarator");
      declarator->add(make("SimpleName", expect_ident()));
      declarator->add(std::move(type));
      var->add(std::move(declarator));
      s->add(std::move(var));
      expect(":");
      s->add(parse_expression());
      expect(")");
      s->add(parse_statement());
      return s;
    }

    auto s = make("ForStmt");
    if (!at(";")) {
      if (starts_local_var_decl()) {
        s->add(parse_local_var_decl());
      } else {
        s->add(parse_expression());
        while (at(",")) { next(); s->add(parse_expression()); }
      }
    }
    expect(";");
    if (!at(";")) s->add(parse_expression());
    expect(";");
    if (!at(")")) {
      s->add(parse_expression());
      while (at(",")) { next(); s->add(parse_expression()); }
    }
    expect(")");
    s->add(parse_statement());
    return s;
  }

  // one label of a case: a constant expression, or a Java 16+ type pattern
  // 'Type ident' (PatternExpr), or 'null'
  JNodePtr parse_case_label() {
    size_t save = pos_;
    if (cur().kind == Tok::kIdent && !kReservedNonType.count(cur().text)) {
      try {
        auto type = parse_type();
        if (cur().kind == Tok::kIdent &&
            !kReservedNonType.count(cur().text) && cur().text != "when") {
          std::string name = expect_ident();
          if (at("->") || at(":") || at(",") || at_ident("when")) {
            auto pat = make("PatternExpr");
            pat->add(std::move(type));
            pat->add(make("SimpleName", name));
            return pat;
          }
        }
      } catch (const ParseError&) {}
      pos_ = save;
    }
    // bare enum-constant arrow label ('case FOO ->'): the primary
    // expression's lambda rule would otherwise eat 'FOO -> body'
    if (cur().kind == Tok::kIdent && !kReservedNonType.count(cur().text) &&
        peek().kind == Tok::kPunct && peek().text == "->") {
      auto ne = make("NameExpr");
      ne->add(make("SimpleName", expect_ident()));
      return ne;
    }
    return parse_expression();
  }

  // both statement and expression switches, classic ':' and arrow '->'
  // entries; javaparser 3.6's entry name is kept for both so classic-corpus
  // path vocab stays stable
  JNodePtr parse_switch(bool as_expr) {
    next();  // switch
    auto s = make(as_expr ? "SwitchExpr" : "SwitchStmt");
    expect("(");
    s->add(parse_expression());
    expect(")");
    expect("{");
    if (as_expr) ++switch_expr_depth_;
    while (!at("}")) {
      auto entry = make("SwitchEntryStmt");  // javaparser 3.6 name
      if (at_ident("case")) {
        next();
        entry->add(parse_case_label());
        while (at(",")) {
          next();
          // Java 21 'case null, default ->': the default marker adds no
          // label node (matching its label-less 'default:' spelling)
          if (at_ident("default")) { next(); continue; }
          entry->add(parse_case_label());
        }
        if (at_ident("when")) {  // Java 21 guarded pattern
          next();
          auto guard = make("Guard");  // wrapper, like ternary's Condition
          guard->add(parse_expression());
          entry->add(std::move(guard));
        }
      } else if (at_ident("default")) {
        next();
      } else {
        fail("expected 'case' or 'default'");
      }
      if (at("->")) {  // Java 14 arrow rule: expr ';' | block | throw
        next();
        if (at("{")) {
          entry->add(parse_block());
        } else if (at_ident("throw")) {
          entry->add(parse_statement());
        } else {
          auto stmt = make("ExpressionStmt");
          stmt->add(parse_expression());
          expect(";");
          entry->add(std::move(stmt));
        }
      } else {
        expect(":");
        while (!at("}") && !at_ident("case") && !at_ident("default"))
          entry->add(parse_statement());
      }
      s->add(std::move(entry));
    }
    if (as_expr) --switch_expr_depth_;
    expect("}");
    return s;
  }

  JNodePtr parse_try() {
    next();  // try
    auto s = make("TryStmt");
    if (at("(")) {  // try-with-resources
      next();
      while (!at(")")) {
        s->add(parse_local_var_decl());
        if (at(";")) next();
      }
      expect(")");
    }
    s->add(parse_block());
    while (at_ident("catch")) {
      next();
      auto clause = make("CatchClause");
      expect("(");
      auto param = make("Parameter");
      if (at_ident("final")) next();
      auto type = parse_type();
      while (at("|")) {  // multi-catch -> UnionType
        next();
        auto union_type = make("UnionType");
        union_type->add(std::move(type));
        union_type->add(parse_type());
        type = std::move(union_type);
        while (at("|")) {
          next();
          type->add(parse_type());
        }
      }
      param->add(std::move(type));
      param->add(make("SimpleName", expect_ident()));
      expect(")");
      clause->add(std::move(param));
      clause->add(parse_block());
      s->add(std::move(clause));
    }
    if (at_ident("finally")) {
      next();
      s->add(parse_block());
    }
    return s;
  }

  // ---- expressions -----------------------------------------------------
  JNodePtr parse_expression() { return parse_assignment(); }

  JNodePtr parse_assignment() {
    auto lhs = parse_ternary();
    static const std::set<std::string> kAssignOps = {
        "=",  "+=", "-=", "*=",  "/=",  "&=",
        "|=", "^=", "%=", "<<=", ">>=", ">>>="};
    if (cur().kind == Tok::kPunct && kAssignOps.count(cur().text)) {
      std::string op = cur().text;
      next();
      auto e = make("AssignExpr");
      e->op = assign_op_name(op);
      e->add(std::move(lhs));
      e->add(parse_assignment());
      return e;
    }
    return lhs;
  }

  JNodePtr parse_ternary() {
    auto cond = parse_binary(0);
    if (at("?")) {
      next();
      auto e = make("ConditionalExpr");
      e->add(std::move(cond));
      e->add(parse_expression());
      expect(":");
      e->add(parse_ternary());
      return e;
    }
    return cond;
  }

  // precedence climbing over binary operators + instanceof
  struct Level { std::set<std::string> ops; };
  static const std::vector<Level>& levels() {
    static const std::vector<Level> kLevels = {
        {{"||"}},
        {{"&&"}},
        {{"|"}},
        {{"^"}},
        {{"&"}},
        {{"==", "!="}},
        {{"<", ">", "<=", ">=", "__instanceof__"}},
        {{"<<", ">>", ">>>"}},
        {{"+", "-"}},
        {{"*", "/", "%"}},
    };
    return kLevels;
  }

  JNodePtr parse_binary(size_t level) {
    if (level >= levels().size()) return parse_unary();
    auto lhs = parse_binary(level + 1);
    while (true) {
      if (levels()[level].ops.count("__instanceof__") && at_ident("instanceof")) {
        next();
        auto e = make("InstanceOfExpr");
        e->add(std::move(lhs));
        auto type = parse_type();
        if (cur().kind == Tok::kIdent &&
            !kReservedNonType.count(cur().text)) {
          // Java 16 pattern: 'x instanceof Type name' binds a variable
          auto pat = make("PatternExpr");
          pat->add(std::move(type));
          pat->add(make("SimpleName", expect_ident()));
          e->add(std::move(pat));
        } else {
          e->add(std::move(type));
        }
        lhs = std::move(e);
        continue;
      }
      if (cur().kind == Tok::kPunct && levels()[level].ops.count(cur().text)) {
        // '<' might open generics of a method call — conservatively treat as
        // operator; generic method calls with explicit type args are rare
        std::string op = cur().text;
        next();
        auto e = make("BinaryExpr");
        e->op = binary_op_name(op);
        e->add(std::move(lhs));
        e->add(parse_binary(level + 1));
        lhs = std::move(e);
        continue;
      }
      break;
    }
    return lhs;
  }

  JNodePtr parse_unary() {
    if (at("+") || at("-") || at("!") || at("~") || at("++") || at("--")) {
      std::string op = cur().text;
      next();
      auto e = make("UnaryExpr");
      if (op == "+") e->op = "PLUS";
      else if (op == "-") e->op = "MINUS";
      else if (op == "!") e->op = "LOGICAL_COMPLEMENT";
      else if (op == "~") e->op = "BITWISE_COMPLEMENT";
      else if (op == "++") e->op = "PREFIX_INCREMENT";
      else if (op == "--") e->op = "PREFIX_DECREMENT";
      e->add(parse_unary());
      return e;
    }
    if (at("(") && looks_like_cast() && !looks_like_lambda_parens()) {
      next();
      auto e = make("CastExpr");
      auto type = parse_type();
      while (at("&")) {  // intersection cast
        next();
        auto intersection = make("IntersectionType");
        intersection->add(std::move(type));
        intersection->add(parse_type());
        type = std::move(intersection);
      }
      e->add(std::move(type));
      expect(")");
      e->add(parse_unary());
      return e;
    }
    return parse_postfix();
  }

  JNodePtr parse_postfix() {
    auto e = parse_primary();
    while (true) {
      if (at(".")) {
        next();
        if (at_ident("new")) fail("qualified new unsupported");
        if (at("<")) {  // explicit type args on call: skip
          int depth = 0;
          do {
            if (at("<")) ++depth;
            else if (at(">")) --depth;
            else if (at(">>")) depth -= 2;
            else if (at(">>>")) depth -= 3;
            next();
          } while (depth > 0 && !at_end());
        }
        if (at_ident("class")) {
          next();
          auto ce = make("ClassExpr");
          ce->add(std::move(e));
          e = std::move(ce);
          continue;
        }
        if (at_ident("this")) {
          next();
          auto te = make("ThisExpr");
          te->add(std::move(e));
          e = std::move(te);
          continue;
        }
        std::string name = expect_ident();
        if (at("(")) {
          auto call = make("MethodCallExpr");
          call->add(std::move(e));  // scope
          call->add(make("SimpleName", name));
          parse_arguments_into(call.get());
          e = std::move(call);
        } else {
          auto fa = make("FieldAccessExpr");
          fa->add(std::move(e));
          fa->add(make("SimpleName", name));
          e = std::move(fa);
        }
        continue;
      }
      if (at("[")) {
        if (peek().kind == Tok::kPunct && peek().text == "]") {
          // array-type method-reference prefix: String[]::new
          next(); expect("]");
          auto at_node = make("ArrayType");
          at_node->add(std::move(e));
          e = std::move(at_node);
          continue;
        }
        next();
        auto ae = make("ArrayAccessExpr");
        ae->add(std::move(e));
        ae->add(parse_expression());
        expect("]");
        e = std::move(ae);
        continue;
      }
      if (at("::")) {
        next();
        auto mr = make("MethodReferenceExpr");
        mr->add(std::move(e));
        mr->text = expect_ident_or_new();
        e = std::move(mr);
        continue;
      }
      if (at("++") || at("--")) {
        auto ue = make("UnaryExpr");
        ue->op = at("++") ? "POSTFIX_INCREMENT" : "POSTFIX_DECREMENT";
        next();
        ue->add(std::move(e));
        e = std::move(ue);
        continue;
      }
      break;
    }
    return e;
  }

  std::string expect_ident_or_new() {
    if (at_ident("new")) { next(); return "new"; }
    return expect_ident();
  }

  void parse_arguments_into(JNode* call) {
    expect("(");
    while (!at(")")) {
      call->add(parse_expression());
      if (at(",")) next();
    }
    expect(")");
  }

  JNodePtr parse_primary() {
    // literals
    if (cur().kind == Tok::kString) {
      auto e = make("StringLiteralExpr", cur().text);
      next();
      return e;
    }
    if (cur().kind == Tok::kChar) {
      auto e = make("CharLiteralExpr", cur().text);
      next();
      return e;
    }
    if (cur().kind == Tok::kInt) {
      auto e = make("IntegerLiteralExpr", cur().text);
      next();
      return e;
    }
    if (cur().kind == Tok::kLong) {
      auto e = make("LongLiteralExpr", cur().text);
      next();
      return e;
    }
    if (cur().kind == Tok::kDouble) {
      auto e = make("DoubleLiteralExpr", cur().text);
      next();
      return e;
    }
    if (at_ident("true") || at_ident("false")) {
      auto e = make("BooleanLiteralExpr", cur().text);
      next();
      return e;
    }
    if (at_ident("null")) {
      next();
      return make("NullLiteralExpr", "null");
    }
    if (at_ident("this")) {
      next();
      return make("ThisExpr", "this");
    }
    if (at_ident("super")) {
      next();
      return make("SuperExpr", "super");
    }
    if (at_ident("new")) return parse_new();

    // lambda: Ident '->' or '(' params ')' '->'
    if (cur().kind == Tok::kIdent && peek().kind == Tok::kPunct &&
        peek().text == "->" && !kReservedNonType.count(cur().text)) {
      auto lambda = make("LambdaExpr");
      auto param = make("Parameter");
      param->add(make("SimpleName", expect_ident()));
      lambda->add(std::move(param));
      expect("->");
      lambda->add(parse_lambda_body());
      return lambda;
    }
    if (at("(") && looks_like_lambda_parens()) {
      auto lambda = make("LambdaExpr");
      next();
      while (!at(")")) {
        auto param = make("Parameter");
        while (at("@")) param->add(parse_annotation());
        if (at_ident("final")) next();
        // typed or bare param
        if (cur().kind == Tok::kIdent && (peek().text == "," || peek().text == ")")) {
          param->add(make("SimpleName", expect_ident()));
        } else {
          param->add(parse_type());
          param->add(make("SimpleName", expect_ident()));
        }
        lambda->add(std::move(param));
        if (at(",")) next();
      }
      expect(")");
      expect("->");
      lambda->add(parse_lambda_body());
      return lambda;
    }
    if (at("(")) {
      next();
      auto e = make("EnclosedExpr");
      e->add(parse_expression());
      expect(")");
      return e;
    }
    if (cur().kind == Tok::kIdent && kPrimitives.count(cur().text)) {
      // e.g. int.class / int[]::new
      auto type = parse_type();
      if (at(".")) {
        next();
        if (at_ident("class")) {
          next();
          auto ce = make("ClassExpr");
          ce->add(std::move(type));
          return ce;
        }
        fail("unexpected primitive member access");
      }
      auto te = make("TypeExpr");
      te->add(std::move(type));
      return te;
    }
    if (at_ident("switch") && peek().kind == Tok::kPunct &&
        peek().text == "(")
      return parse_switch(/*as_expr=*/true);
    if (cur().kind == Tok::kIdent && !kReservedNonType.count(cur().text)) {
      std::string name = expect_ident();
      if (at("(")) {
        auto call = make("MethodCallExpr");  // unscoped call
        call->add(make("SimpleName", name));
        parse_arguments_into(call.get());
        return call;
      }
      auto ne = make("NameExpr");
      ne->add(make("SimpleName", name));
      return ne;
    }
    fail("expected expression");
  }

  JNodePtr parse_lambda_body() {
    if (at("{")) return parse_block();
    auto stmt = make("ExpressionStmt");
    stmt->add(parse_expression());
    return stmt;
  }

  JNodePtr parse_new() {
    next();  // new
    // array creation?
    auto type = (cur().kind == Tok::kIdent && kPrimitives.count(cur().text))
                    ? [&] { auto t = make("PrimitiveType", cur().text); next(); return t; }()
                    : parse_class_type();
    if (at("[")) {
      auto e = make("ArrayCreationExpr");
      e->add(std::move(type));
      bool saw_dim = false;
      while (at("[")) {
        next();
        auto lvl = make("ArrayCreationLevel");
        if (!at("]")) {
          lvl->add(parse_expression());
          saw_dim = true;
        } else {
          lvl->text = "[]";
        }
        expect("]");
        e->add(std::move(lvl));
      }
      if (at("{")) {
        e->add(parse_variable_initializer());
      }
      (void)saw_dim;
      return e;
    }
    auto e = make("ObjectCreationExpr");
    e->add(std::move(type));
    parse_arguments_into(e.get());
    if (at("{")) {  // anonymous class body
      parse_class_body_into(e.get(), false);
    }
    return e;
  }

  static JNodePtr clone(const JNode* n) {
    auto copy = make(n->type, n->text);
    copy->op = n->op;
    copy->is_var_args = n->is_var_args;
    for (const auto& c : n->children) copy->add(clone(c.get()));
    return copy;
  }

  static const std::set<std::string> kReservedNonType;

  const std::string& source_;
  Lexer lexer_;
  std::vector<Token> toks_;
  size_t pos_ = 0;
  int switch_expr_depth_ = 0;
};

const std::set<std::string> Parser::kReservedNonType = {
    "abstract", "assert",   "break",     "case",       "catch",  "class",
    "const",    "continue", "default",   "do",         "else",   "enum",
    "extends",  "final",    "finally",   "for",        "goto",   "if",
    "implements", "import", "instanceof", "interface", "native", "new",
    "package",  "private",  "protected", "public",     "return", "static",
    "strictfp", "super",    "switch",    "synchronized", "this", "throw",
    "throws",   "transient", "try",      "volatile",   "while",  "true",
    "false",    "null"};

}  // namespace

JNodePtr parse_compilation_unit(const std::string& source) {
  Parser parser(source);
  return parser.run();
}

std::string node_source(const JNode& n) {
  // leaf terminal text: identifiers/literals carry their lexeme; composite
  // leaves print their minimal source form
  if (!n.text.empty()) return n.text;
  if (n.type == "WildcardType") return "?";
  if (n.type == "ArrayCreationLevel") return "[]";
  // fallback: reconstruct from children (e.g. qualified Name)
  std::string out;
  for (const auto& c : n.children) {
    if (!out.empty()) out += ".";
    out += node_source(*c);
  }
  return out;
}

}  // namespace c2v
