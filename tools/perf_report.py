#!/usr/bin/env python
"""Perf report + CI regression sentinel over bench serve output.

Two modes:

**Report** — pretty-print the sentinel metrics extracted from one or more
bench output files (the merged stdout/stderr stream of ``bench.py
--serve``, or a ``BENCH_rN.json`` stamp whose ``raw`` field carries it)::

    python tools/perf_report.py /tmp/serve_bench.json

**Check** — compare a fresh run against the committed baseline and exit
nonzero on regression (the CI ``perf-sentinel`` job)::

    env JAX_PLATFORMS=cpu BENCH_SUPERVISED=1 \\
        python bench.py --serve > /tmp/m.json 2> /tmp/d.json
    cat /tmp/d.json /tmp/m.json > /tmp/serve_bench.json
    python tools/perf_report.py --check \\
        --baseline tools/perf_baseline.json --current /tmp/serve_bench.json

Every gate is a RATIO against the baseline (or a structural invariant),
never an absolute wall-clock number — shared CI runners make absolute
latency/QPS gating pure noise. The gated metrics:

- ``pad_efficiency``        may not drop more than ``--tol-pad`` (15%)
- ``device_calls_per_request`` may not grow more than ``--tol-calls`` (25%)
- ``post_warmup_recompiles``   may not exceed the baseline (normally 0)
- ``mfu``                   must stay within (0, 1] and above
                            ``--mfu-floor`` (10%) of the baseline — the
                            loose floor absorbs host-speed variance while
                            still catching order-of-magnitude decay
- ``coalesce_mean``         may not drop more than ``--tol-coalesce`` (50%;
                            coalescing is arrival-timing sensitive)

``--update-baseline`` rewrites the baseline file from the current run
(commit the result when a perf change is intentional).
"""

from __future__ import annotations

import argparse
import json
import sys

# metric -> (direction, default tolerance); direction "min" = current may
# not drop below baseline*(1-tol), "max" = may not exceed baseline*(1+tol)
GATES = {
    "pad_efficiency": ("min", 0.15),
    "device_calls_per_request": ("max", 0.25),
    "post_warmup_recompiles": ("max", 0.0),
    "mfu": ("min", 0.90),  # i.e. floor = 10% of baseline; see --mfu-floor
    "coalesce_mean": ("min", 0.50),
}

INFO_METRICS = ("qps", "p50_ms", "p99_ms", "busy_fraction")


def load_records(path: str) -> list[dict]:
    """Parse a bench output file: JSON-lines (logging noise skipped), a
    single JSON object, or a BENCH_rN.json stamp with a ``raw`` stream."""
    with open(path, encoding="utf-8") as f:
        text = f.read()
    records: list[dict] = []
    try:
        whole = json.loads(text)
    except ValueError:
        whole = None
    if isinstance(whole, dict):
        records.append(whole)
        raw = whole.get("raw")
        if isinstance(raw, str):
            records.extend(_parse_lines(raw))
        parsed = whole.get("parsed")
        if isinstance(parsed, dict):
            records.append(parsed)
        return records
    if isinstance(whole, list):
        return [r for r in whole if isinstance(r, dict)]
    return _parse_lines(text)


def _parse_lines(text: str) -> list[dict]:
    records = []
    for line in text.splitlines():
        line = line.strip()
        if not line or not line.startswith("{"):
            continue
        try:
            obj = json.loads(line)
        except ValueError:
            continue
        if isinstance(obj, dict):
            records.append(obj)
    return records


def serve_metrics(records: list[dict]) -> dict:
    """Sentinel metrics from the LAST serve-mode detail + metric line."""
    detail = None
    metric = None
    for obj in records:
        d = obj.get("detail")
        if isinstance(d, dict) and d.get("mode") == "serve":
            detail = d
        if obj.get("metric") == "serve_requests_per_sec":
            metric = obj
    out: dict = {}
    if detail is not None:
        counters = detail.get("counters") or {}
        completed = detail.get("completed") or 0
        batches = counters.get("serve_batches")
        out["pad_efficiency"] = detail.get("pad_efficiency")
        if batches is not None and completed:
            out["device_calls_per_request"] = round(batches / completed, 4)
        out["post_warmup_recompiles"] = detail.get("post_warmup_recompiles")
        out["coalesce_mean"] = detail.get("coalesce_mean")
        out["qps"] = detail.get("qps")
        lat = (detail.get("latency_ms") or {}).get("e2e") or {}
        out["p50_ms"] = lat.get("p50_ms")
        out["p99_ms"] = lat.get("p99_ms")
        perf = detail.get("perf") or {}
        out["mfu"] = perf.get("mfu")
        out["busy_fraction"] = perf.get("busy_fraction")
        out["device_kind"] = perf.get("device_kind")
    if metric is not None:
        out.setdefault("mfu", metric.get("mfu"))
        out.setdefault("post_warmup_recompiles",
                       metric.get("post_warmup_recompiles"))
    return {k: v for k, v in out.items() if v is not None}


def compare(baseline: dict, current: dict, tolerances: dict) -> list[str]:
    """Ratio gates; returns human-readable failure strings (empty = OK)."""
    failures = []
    mfu = current.get("mfu")
    if mfu is not None and not (0.0 < mfu <= 1.0):
        failures.append(
            f"mfu={mfu} violates the 0 < mfu <= 1 invariant "
            "(achieved FLOP/s exceeded the device peak — the cost model "
            "or the peak table is wrong)"
        )
    for name, (direction, _default) in GATES.items():
        tol = tolerances[name]
        base = baseline.get(name)
        cur = current.get(name)
        if base is None:
            continue  # baseline never recorded it — nothing to gate
        if cur is None:
            failures.append(
                f"{name}: present in baseline ({base}) but missing from "
                "the current run — the bench stopped reporting it"
            )
            continue
        if direction == "min":
            floor = base * (1.0 - tol)
            if cur < floor:
                failures.append(
                    f"{name}: {cur} < {floor:.4g} "
                    f"(baseline {base} - {tol:.0%} tolerance)"
                )
        else:
            ceiling = base * (1.0 + tol) if base else tol
            if cur > ceiling:
                failures.append(
                    f"{name}: {cur} > {ceiling:.4g} "
                    f"(baseline {base} + {tol:.0%} tolerance)"
                )
    return failures


def _print_table(rows: list[tuple[str, dict]]) -> None:
    keys = list(GATES) + [k for k in INFO_METRICS]
    width = max(len(k) for k in keys) + 2
    header = "metric".ljust(width) + "  ".join(
        name.rjust(18) for name, _ in rows
    )
    print(header)
    print("-" * len(header))
    for key in keys:
        cells = []
        for _, metrics in rows:
            value = metrics.get(key)
            cells.append(("-" if value is None else str(value)).rjust(18))
        gate = "*" if key in GATES else " "
        print(f"{key.ljust(width - 2)}{gate} " + "  ".join(cells))
    print("(* = gated by --check; others informational)")


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        description=__doc__, formatter_class=argparse.RawDescriptionHelpFormatter
    )
    parser.add_argument("files", nargs="*", help="bench output files to report")
    parser.add_argument("--check", action="store_true",
                        help="gate --current against --baseline; exit "
                        "nonzero on regression")
    parser.add_argument("--baseline", default="tools/perf_baseline.json")
    parser.add_argument("--current",
                        help="fresh bench output to check/update from")
    parser.add_argument("--update-baseline", action="store_true",
                        help="rewrite --baseline from --current")
    parser.add_argument("--tol-pad", type=float, default=GATES["pad_efficiency"][1])
    parser.add_argument("--tol-calls", type=float,
                        default=GATES["device_calls_per_request"][1])
    parser.add_argument("--tol-recompiles", type=float,
                        default=GATES["post_warmup_recompiles"][1])
    parser.add_argument("--mfu-floor", type=float, default=GATES["mfu"][1],
                        help="mfu may drop this fraction below baseline "
                        "(default 0.9: fail only below 10%% of baseline)")
    parser.add_argument("--tol-coalesce", type=float,
                        default=GATES["coalesce_mean"][1])
    args = parser.parse_args(argv)
    tolerances = {
        "pad_efficiency": args.tol_pad,
        "device_calls_per_request": args.tol_calls,
        "post_warmup_recompiles": args.tol_recompiles,
        "mfu": args.mfu_floor,
        "coalesce_mean": args.tol_coalesce,
    }

    if args.update_baseline:
        if not args.current:
            parser.error("--update-baseline needs --current")
        metrics = serve_metrics(load_records(args.current))
        if not metrics:
            print(f"no serve metrics found in {args.current}", file=sys.stderr)
            return 2
        with open(args.baseline, "w", encoding="utf-8") as f:
            json.dump(metrics, f, indent=1, sort_keys=True)
            f.write("\n")
        print(f"baseline {args.baseline} updated: "
              f"{json.dumps(metrics, sort_keys=True)}")
        return 0

    if args.check:
        if not args.current:
            parser.error("--check needs --current")
        try:
            with open(args.baseline, encoding="utf-8") as f:
                baseline = json.load(f)
        except (OSError, ValueError) as exc:
            print(f"cannot load baseline {args.baseline}: {exc}",
                  file=sys.stderr)
            return 2
        current = serve_metrics(load_records(args.current))
        if not current:
            print(f"no serve metrics found in {args.current}",
                  file=sys.stderr)
            return 2
        _print_table([("baseline", baseline), ("current", current)])
        failures = compare(baseline, current, tolerances)
        if failures:
            print("\nPERF REGRESSION:", file=sys.stderr)
            for failure in failures:
                print(f"  - {failure}", file=sys.stderr)
            return 1
        print("\nperf sentinel: OK (all ratio gates within tolerance)")
        return 0

    if not args.files:
        parser.error("give bench output files, or --check/--update-baseline")
    rows = []
    for path in args.files:
        metrics = serve_metrics(load_records(path))
        rows.append((path.rsplit("/", 1)[-1], metrics))
    _print_table(rows)
    return 0


if __name__ == "__main__":
    sys.exit(main())
