"""A/B the two per-epoch context-sampling schemes at a scale where they
differ (methods with more contexts than the bag):

- A (host pipeline, reference parity): fresh uniform subsample WITHOUT
  replacement each epoch (model/dataset_builder.py:134-135 semantics);
- B (device epochs): rotation WINDOW over a once-shuffled context order
  (train/device_epoch.py module docstring).

Trains the same model/recipe on the same synthetic corpus with both and
prints one JSON line with the F1 trajectories. CPU-friendly (~2 min).
"""

from __future__ import annotations

import json
import sys

import numpy as np

sys.path.insert(0, ".")


def main() -> None:
    import jax

    jax.config.update("jax_platforms", "cpu")

    from code2vec_tpu.data.synth import SynthSpec, corpus_data_from_raw, generate_corpus_data
    from code2vec_tpu.train.config import TrainConfig
    from code2vec_tpu.train.loop import train

    # oversized bags: mean 60 contexts vs bag 24, so ~90% of methods
    # actually subsample and the schemes can diverge
    spec = SynthSpec(
        n_methods=2500,
        n_terminals=1200,
        n_paths=900,
        n_labels=40,
        mean_contexts=60.0,
        max_contexts=150,
        seed=0,
    )
    data = corpus_data_from_raw(generate_corpus_data(spec))
    base = dict(
        max_epoch=10,
        batch_size=64,
        encode_size=64,
        terminal_embed_size=32,
        path_embed_size=32,
        max_path_length=24,
        print_sample_cycle=0,
        early_stop_patience=100,
    )

    host = train(TrainConfig(**base), data)
    dev = train(TrainConfig(**base, device_epoch=True, device_chunk_batches=8), data)

    print(
        json.dumps(
            {
                "subsample_fraction": float(
                    np.mean(np.diff(data.row_splits) > base["max_path_length"])
                ),
                "host_uniform_f1": [round(h["f1"], 4) for h in host.history],
                "device_window_f1": [round(h["f1"], 4) for h in dev.history],
                "host_best_f1": round(host.best_f1, 4),
                "device_best_f1": round(dev.best_f1, 4),
            }
        )
    )


if __name__ == "__main__":
    main()
