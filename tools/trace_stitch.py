#!/usr/bin/env python
"""Merge per-process Chrome trace files into ONE fleet-wide trace.

Every process in a serving fleet writes its own ``trace-p<i>.json``
(``obs/trace.py``): the router under ``--trace_dir``, each replica under
``--trace_dir/r<slot>``. Those files already share one time axis — span
timestamps are unix-epoch-anchored microseconds (PR 2), aligned across
hosts up to NTP skew — but they collide on ``pid`` (every single-host
process exports as process 0) and nothing ties a router span to the
replica work it caused. This tool fixes both:

- **stitch**: each input file gets a fresh pid; its ``process_name``
  metadata row is prefixed with the file's source label (``r0:``,
  ``r1:`` — the directory the fleet CLI wrote it under), so the merged
  trace shows the router row and every replica row aligned on one
  timeline, loadable in Perfetto / ``chrome://tracing`` unchanged.
- **index**: spans tagged with a request trace id (``args.trace_id``, or
  the coalesce-aware ``args.trace_ids`` list a batched device span
  carries for the N requests it served) are grouped per trace id — the
  cross-process request path: ``fleet_request`` (router) ->
  ``serve_request`` (worker resolver) -> ``serve_pad``/``serve_device``
  (micro-batcher) -> ``engine_run`` (executable call), one id end to end.

Usage::

    python tools/trace_stitch.py --out merged.json TRACE_DIR [MORE...]
    python tools/trace_stitch.py --index-out index.json fleet_traces/
    python tools/trace_stitch.py --trace-id 8f2a... fleet_traces/

Inputs are trace files or directories (searched recursively for
``trace-p*.json``). A one-line JSON summary lands on stdout: file/event
counts, distinct trace ids, and how many trace ids cross processes.
With ``--trace-id`` the tool instead prints that ONE request's
critical path as a per-hop ms table (offset from the request's first
span, duration, process, coalesce flag) — the mid-incident view that
otherwise needs a Chrome-trace load.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys

__all__ = [
    "critical_path_table",
    "find_trace_files",
    "stitch_traces",
    "trace_index",
]


def find_trace_files(paths: list[str]) -> list[str]:
    """Expand files/directories into a sorted list of trace-p*.json files
    (directories searched recursively)."""
    found: list[str] = []
    for path in paths:
        if os.path.isdir(path):
            found.extend(
                glob.glob(
                    os.path.join(path, "**", "trace-p*.json"), recursive=True
                )
            )
        elif os.path.isfile(path):
            found.append(path)
        else:
            raise FileNotFoundError(f"no trace file or directory at {path!r}")
    # stable order: label (relative path) sorts router before r0 before r1
    return sorted(dict.fromkeys(os.path.abspath(p) for p in found))


def _source_label(path: str, root: str) -> str:
    """The per-file row label: the file's directory relative to the
    common root ('' for files directly in the root — typically the
    router's own trace)."""
    rel = os.path.relpath(os.path.dirname(path), root)
    return "" if rel == "." else rel.replace(os.sep, "/")


def stitch_traces(paths: list[str]) -> dict:
    """Merge trace files into one Chrome trace object: per-file pid
    remapping, source-labeled process rows, events in timestamp order.
    Timestamps are passed through untouched — the files are already
    epoch-anchored onto one shared axis."""
    if not paths:
        raise ValueError("no trace files to stitch")
    root = os.path.commonpath([os.path.dirname(p) for p in paths])
    meta: list[dict] = []
    events: list[dict] = []
    sources: list[dict] = []
    dropped = 0
    for new_pid, path in enumerate(paths):
        with open(path, encoding="utf-8") as f:
            trace = json.load(f)
        label = _source_label(path, root)
        dropped += int(trace.get("dropped_events", 0) or 0)
        n_events = 0
        named = False
        for event in trace.get("traceEvents", []):
            event = dict(event, pid=new_pid)
            if event.get("ph") == "M":
                if event.get("name") == "process_name":
                    named = True
                    name = (event.get("args") or {}).get("name", "")
                    event["args"] = {
                        "name": f"{label}: {name}" if label else name
                    }
                meta.append(event)
            else:
                events.append(event)
                n_events += 1
        if not named:  # a file without naming metadata still gets a row
            meta.append({
                "name": "process_name", "ph": "M", "pid": new_pid,
                "args": {"name": label or os.path.basename(path)},
            })
        sources.append({
            "pid": new_pid, "path": path, "label": label, "events": n_events,
        })
    events.sort(key=lambda e: e.get("ts", 0))
    merged: dict = {
        "traceEvents": meta + events,
        "displayTimeUnit": "ms",
        "stitch": {"sources": sources},
    }
    if dropped:
        merged["dropped_events"] = dropped
    return merged


def trace_index(trace: dict) -> dict:
    """Group a (stitched) trace's spans by request trace id.

    Returns ``{trace_id: {"spans": [...], "processes": [...]}}`` where
    each span entry carries the process label, span name, ts, dur, and
    whether the link came through a batched span's ``trace_ids`` list
    (``coalesced: true`` — the device call served N requests at once).
    """
    labels = {
        s["pid"]: (s["label"] or "router")
        for s in trace.get("stitch", {}).get("sources", [])
    }
    index: dict[str, dict] = {}

    def add(trace_id: str, event: dict, coalesced: bool) -> None:
        entry = index.setdefault(
            str(trace_id), {"spans": [], "processes": []}
        )
        process = labels.get(
            event.get("pid"), f"p{event.get('pid')}"
        )
        entry["spans"].append({
            "process": process,
            "name": event.get("name"),
            "ts": event.get("ts"),
            "dur": event.get("dur"),
            "coalesced": coalesced,
        })
        if process not in entry["processes"]:
            entry["processes"].append(process)

    for event in trace.get("traceEvents", []):
        if event.get("ph") == "M":
            continue
        args = event.get("args") or {}
        trace_id = args.get("trace_id")
        if isinstance(trace_id, str) and trace_id:
            add(trace_id, event, coalesced=False)
        trace_ids = args.get("trace_ids")
        if isinstance(trace_ids, list):
            for tid in trace_ids:
                if isinstance(tid, str) and tid:
                    add(tid, event, coalesced=True)
    for entry in index.values():
        entry["spans"].sort(key=lambda s: (s["ts"] or 0))
    return index


def critical_path_table(trace_id: str, entry: dict) -> str:
    """Render one indexed request as a per-hop ms table.

    Spans are already ts-sorted (``trace_index``); offsets are relative
    to the request's first span, so the table reads top-to-bottom as the
    request's life: router admission -> worker resolver -> pad -> device
    -> postprocess. ``ts``/``dur`` are Chrome-trace microseconds.
    """
    spans = entry.get("spans") or []
    if not spans:
        return f"trace {trace_id}: no spans"
    t0 = min(s["ts"] for s in spans if s.get("ts") is not None)
    end = max(
        (s["ts"] or 0) + (s["dur"] or 0)
        for s in spans
        if s.get("ts") is not None
    )
    rows = []
    for span in spans:
        ts, dur = span.get("ts"), span.get("dur")
        rows.append((
            span.get("process") or "?",
            span.get("name") or "?",
            f"{(ts - t0) / 1e3:+.3f}" if ts is not None else "?",
            f"{dur / 1e3:.3f}" if dur is not None else "?",
            "coalesced" if span.get("coalesced") else "",
        ))
    headers = ("process", "span", "start_ms", "dur_ms", "")
    widths = [
        max(len(headers[i]), max(len(r[i]) for r in rows))
        for i in range(len(headers))
    ]
    lines = [
        f"trace {trace_id}: {len(spans)} spans across "
        f"{len(entry.get('processes') or [])} processes, "
        f"critical path {(end - t0) / 1e3:.3f} ms",
        "  ".join(h.ljust(w) for h, w in zip(headers, widths)).rstrip(),
    ]
    lines.append("-" * len(lines[-1]))
    for row in rows:
        lines.append(
            "  ".join(c.ljust(w) for c, w in zip(row, widths)).rstrip()
        )
    return "\n".join(lines)


def main(argv: list[str] | None = None) -> None:
    parser = argparse.ArgumentParser(
        prog="trace_stitch",
        description="merge per-process Chrome traces into one fleet-wide "
        "trace and index spans by request trace id",
    )
    parser.add_argument("inputs", nargs="+",
                        help="trace files or directories (searched "
                        "recursively for trace-p*.json)")
    parser.add_argument("--out", default=None,
                        help="write the merged Chrome trace here "
                        "(viewable in Perfetto / chrome://tracing)")
    parser.add_argument("--index-out", default=None,
                        help="write the per-trace-id span index here")
    parser.add_argument("--trace-id", default=None,
                        help="print ONE request's critical path as a "
                        "per-hop ms table and exit")
    args = parser.parse_args(argv)

    paths = find_trace_files(args.inputs)
    if not paths:
        raise SystemExit("no trace-p*.json files found under the inputs")
    merged = stitch_traces(paths)
    if args.out:
        with open(args.out, "w", encoding="utf-8") as f:
            json.dump(merged, f)
    index = trace_index(merged)
    if args.trace_id is not None:
        entry = index.get(args.trace_id)
        if entry is None:
            raise SystemExit(
                f"trace id {args.trace_id!r} not found "
                f"({len(index)} trace ids indexed)"
            )
        print(critical_path_table(args.trace_id, entry))
        return
    if args.index_out:
        with open(args.index_out, "w", encoding="utf-8") as f:
            json.dump(index, f, indent=1)
    n_events = sum(
        1 for e in merged["traceEvents"] if e.get("ph") != "M"
    )
    summary = {
        "files": len(paths),
        "events": n_events,
        "traces": len(index),
        "cross_process_traces": sum(
            1 for entry in index.values() if len(entry["processes"]) > 1
        ),
        "out": args.out,
        "index_out": args.index_out,
    }
    json.dump(summary, sys.stdout)
    sys.stdout.write("\n")


if __name__ == "__main__":
    main()
