#!/usr/bin/env python
"""Thin wrapper: ``python tools/fleet_serve.py`` == ``python -m
code2vec_tpu.serve.fleet`` (router + N replica workers + rolling live
checkpoint hot-swap; see docs/ARCHITECTURE.md "Fleet serving")."""

from code2vec_tpu.serve.fleet.__main__ import main

if __name__ == "__main__":
    main()
