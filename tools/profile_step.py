"""Microbenchmark: split the train-step time into sampling / fwd / fwd+bwd /
full step to find the bottleneck. Not part of the package; dev tool."""

import time
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from code2vec_tpu.data.synth import SynthSpec, generate_corpus_data
from code2vec_tpu.data.vocab import Vocab
from code2vec_tpu.data.reader import CorpusData
from code2vec_tpu.models.code2vec import Code2Vec, Code2VecConfig
from code2vec_tpu.train.config import TrainConfig
from code2vec_tpu.train.device_epoch import EpochRunner, stage_method_corpus, _sample_batch
from code2vec_tpu.train.step import create_train_state, build_train_step_fn

B, L = 1024, 200
spec = SynthSpec(n_methods=8192, n_terminals=360_631, n_paths=342_845,
                 n_labels=8_000, mean_contexts=120.0, max_contexts=400, seed=0)
raw = generate_corpus_data(spec)
label_vocab = Vocab()
for name in raw.label_names:
    label_vocab.add_label(name)
data = CorpusData(
    starts=raw.starts + 1, paths=raw.paths, ends=raw.ends + 1,
    row_splits=raw.row_splits, ids=np.arange(spec.n_methods, dtype=np.int64),
    labels=raw.label_ids.astype(np.int32), normalized_labels=[],
    sources=[None] * spec.n_methods, aliases=[{} for _ in range(spec.n_methods)],
    terminal_vocab=Vocab(), path_vocab=Vocab(), label_vocab=label_vocab)
data.terminal_vocab.add("<PAD/>", 0)
data.terminal_vocab.add("@question", 1)
data.terminal_vocab.add("@method_0", 2)

mc = Code2VecConfig(
    terminal_count=spec.n_terminals + 2, path_count=spec.n_paths + 1,
    label_count=len(label_vocab), terminal_embed_size=100, path_embed_size=100,
    encode_size=100, dropout_prob=0.25, dtype=jnp.bfloat16)
tc = TrainConfig(batch_size=B, max_path_length=L)

rng = np.random.default_rng(0)
staged = stage_method_corpus(data, np.arange(data.n_items), rng)
rows = jnp.asarray(rng.integers(0, data.n_items, B).astype(np.int32))
valid = jnp.ones(B, jnp.float32)
key = jax.random.PRNGKey(0)

sample = jax.jit(partial(_sample_batch, bag=L))
batch = sample(staged.contexts, staged.row_splits, staged.labels, rows, valid, key=key)
batch = jax.device_put(batch)

state = create_train_state(tc, mc, jax.random.PRNGKey(0), jax.tree.map(np.asarray, batch))
cw = jnp.ones(mc.label_count, jnp.float32)
raw_train = build_train_step_fn(mc, cw)
train = jax.jit(raw_train, donate_argnums=0)

model = Code2Vec(mc)

@jax.jit
def fwd(params, batch):
    logits, _, _ = model.apply({"params": params}, batch["starts"], batch["paths"],
                               batch["ends"], deterministic=True)
    return logits.sum()

def loss_fn(params, batch, key):
    logits, _, _ = model.apply({"params": params}, batch["starts"], batch["paths"],
                               batch["ends"], deterministic=False, rngs={"dropout": key})
    return logits.astype(jnp.float32).sum()

grad = jax.jit(jax.grad(loss_fn))

def bench(name, fn, *args, n=30, **kw):
    out = fn(*args, **kw)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(n):
        out = fn(*args, **kw)
    jax.block_until_ready(out)
    dt = (time.perf_counter() - t0) / n * 1e3
    print(f"{name:28s} {dt:8.3f} ms")
    return dt

bench("sample_batch", sample, staged.contexts, staged.row_splits, staged.labels, rows, valid, key=key)
bench("forward", fwd, state.params, batch)
bench("grad (fwd+bwd)", grad, state.params, batch, key)

# full step without donation pitfalls: rebuild state each call is costly; instead
# time N chained steps
@jax.jit
def steps10(state, batch):
    def body(s, _):
        s, loss = raw_train(s, batch)
        return s, loss
    state, losses = jax.lax.scan(body, state, None, length=10)
    return state, losses.sum()

st = state
out = steps10(st, batch); jax.block_until_ready(out[1])
t0 = time.perf_counter()
for _ in range(10):
    st, l = steps10(st, batch)
jax.block_until_ready(l)
print(f"{'full step (scan/10)':28s} {(time.perf_counter()-t0)/100*1e3:8.3f} ms")
