"""Component attribution for the flagship train step (VERDICT r3 #2: where
do 25.3 ms go, against a ~4-6 ms HBM roofline?).

Times each piece of the step in isolation on the current backend:

- ``sample``        on-device CSR batch assembly (gathers + randint subsample)
- ``forward``       embedding gathers + encoder + attention pool + head
- ``grad``          full fwd+bwd including the embedding-table scatter-adds
- ``grad_frozen``   fwd+bwd with stop_gradient on the embedding lookups —
                    the same compute minus table grads; ``grad - grad_frozen``
                    isolates the scatter-add + table-grad materialization
- ``adam``          optimizer update alone on precomputed grads (the
                    full-table moment read-modify-write: ~2.2 GB/step at
                    top11 scale with f32 moments)
- ``step``          one fused train step (scan of 1)
- ``chunk/N``       the production scanned chunk, per-step — vs ``step``
                    shows dispatch amortization

Recipe knobs via env (defaults = the measured round-3 winner):
PROF_DTYPE=float32|bfloat16  PROF_EMBED_GRAD=dense|segment|segment_sorted
PROF_RNG_IMPL=unsafe_rbg|threefry2x32  PROF_ADAM_MU_DTYPE=float32|bfloat16
PROF_BATCH, PROF_BAG, PROF_CHUNK, PROF_TRACE_DIR (jax.profiler trace of the
chunk when set).

Prints one JSON line per row, then a markdown table for ARCHITECTURE.md.
"""

import json
import os
import signal
import sys
import time
from functools import partial

# runnable as `python tools/profile_step.py` from the repo root (sys.path[0]
# is tools/, not the cwd)
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

if os.environ.get("JAX_PLATFORMS", "").strip():
    # the axon plugin pre-empts the env var; re-assert via the config API
    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"].strip())

import jax.numpy as jnp
import numpy as np

from code2vec_tpu.data.synth import SynthSpec, corpus_data_from_raw, generate_corpus_data
from code2vec_tpu.models.code2vec import Code2Vec, Code2VecConfig
from code2vec_tpu.train.config import TrainConfig
from code2vec_tpu.train.device_epoch import EpochRunner, stage_method_corpus, _sample_batch
from code2vec_tpu.train.step import build_train_step_fn, create_train_state, weighted_nll

B = int(os.environ.get("PROF_BATCH", 1024))
L = int(os.environ.get("PROF_BAG", 200))
CHUNK = int(os.environ.get("PROF_CHUNK", 16))
DTYPE = (
    jnp.bfloat16
    if os.environ.get("PROF_DTYPE", "float32").strip().lower() in ("bfloat16", "bf16")
    else jnp.float32
)
EMBED_GRAD = os.environ.get("PROF_EMBED_GRAD", "dense")
RNG_IMPL = os.environ.get("PROF_RNG_IMPL", "unsafe_rbg")
ADAM_MU_DTYPE = os.environ.get("PROF_ADAM_MU_DTYPE", "float32")
ATTN_IMPL = os.environ.get("PROF_ATTN_IMPL", "xla")
ENCODER_IMPL = os.environ.get("PROF_ENCODER_IMPL", "concat")

print(json.dumps({"backend": jax.default_backend(), "batch": B, "bag": L,
                  "dtype": DTYPE.__name__, "embed_grad": EMBED_GRAD,
                  "rng_impl": RNG_IMPL, "adam_mu_dtype": ADAM_MU_DTYPE,
                  "attn_impl": ATTN_IMPL, "encoder_impl": ENCODER_IMPL}),
      flush=True)

results = {}


def _partial_summary(signum, frame):  # noqa: ARG001 - signal signature
    """The watcher runs this under ``timeout -k`` (TERM, then KILL after a
    grace) — and a wedged tunnel can hang any single bench forever. On a
    TERM that actually gets delivered (i.e. the main thread is in Python,
    not blocked in a native XLA call — CPython defers handlers inside C
    calls, which is why the watcher's ``-k`` KILL backstop is REQUIRED),
    dump whatever components already measured so the window isn't a total
    loss, then exit nonzero. Re-arms SIG_DFL first so a second TERM kills
    immediately even if this handler's own I/O wedges."""
    signal.signal(signal.SIGTERM, signal.SIG_DFL)
    print(json.dumps({"partial": True, "components": {
        k: round(v, 3) for k, v in results.items()
    }}), flush=True)
    raise SystemExit(124)


signal.signal(signal.SIGTERM, _partial_summary)

spec = SynthSpec(n_methods=max(B * 8, 8192), n_terminals=360_631,
                 n_paths=342_845, n_labels=8_000, mean_contexts=120.0,
                 max_contexts=400, seed=0)
data = corpus_data_from_raw(generate_corpus_data(spec))

mc = Code2VecConfig(
    terminal_count=spec.n_terminals + 2, path_count=spec.n_paths + 1,
    label_count=len(data.label_vocab), terminal_embed_size=100,
    path_embed_size=100, encode_size=100, dropout_prob=0.25, dtype=DTYPE,
    embed_grad=EMBED_GRAD, attn_impl=ATTN_IMPL, encoder_impl=ENCODER_IMPL)
tc = TrainConfig(batch_size=B, max_path_length=L, rng_impl=RNG_IMPL,
                 adam_mu_dtype=ADAM_MU_DTYPE)

rng = np.random.default_rng(0)
staged = stage_method_corpus(data, np.arange(data.n_items), rng)
rows = jnp.asarray(rng.integers(0, data.n_items, B).astype(np.int32))
valid = jnp.ones(B, jnp.float32)
key = jax.random.PRNGKey(0)

sample = jax.jit(partial(_sample_batch, bag=L))
batch = jax.device_put(sample(staged.contexts, staged.row_splits,
                              staged.labels, rows, valid, key=key))

state = create_train_state(tc, mc, jax.random.PRNGKey(0),
                           jax.tree.map(np.asarray, batch))
cw = jnp.ones(mc.label_count, jnp.float32)
raw_train = build_train_step_fn(mc, cw)
model = Code2Vec(mc)

def bench(name, fn, *args, n=30, **kw):
    out = fn(*args, **kw)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(n):
        out = fn(*args, **kw)
    jax.block_until_ready(out)
    dt = (time.perf_counter() - t0) / n * 1e3
    results[name] = dt
    print(json.dumps({"component": name, "ms": round(dt, 3)}), flush=True)
    return dt


# --- sampling ------------------------------------------------------------
bench("sample", sample, staged.contexts, staged.row_splits, staged.labels,
      rows, valid, key=key)


# --- forward -------------------------------------------------------------
@jax.jit
def fwd(params, batch):
    logits, _, _ = model.apply({"params": params}, batch["starts"],
                               batch["paths"], batch["ends"], deterministic=True)
    return logits.astype(jnp.float32).sum()


bench("forward", fwd, state.params, batch)


# --- kernel variants (ops/fused_encode_pool.py) ---------------------------
# Pallas forward rows: pool-only vs gather-split vs fully-fused (+ int8
# fused), with the autotuned schedule consulted/recorded for provenance.
# The rows run whenever the resolved lowering strategy (ops/backend.py)
# compiles — TPU kernels on TPU, the compiled CPU strategy elsewhere; only
# an interpreting resolution (e.g. C2V_KERNEL_BACKEND=interpret) makes
# them opt-in, because interpreter numbers characterize the interpreter.
_kern_env = os.environ.get("PROF_KERNEL_VARIANTS", "auto").strip().lower()
from code2vec_tpu.ops.backend import resolve as _resolve_kernel_backend

_kern_strategy = _resolve_kernel_backend()
if _kern_env in ("1", "true", "yes", "on") or (
    _kern_env == "auto" and not _kern_strategy.interpret
):
    from code2vec_tpu.ops.autotune import counters_snapshot, lookup_schedule
    from code2vec_tpu.ops.quant import quantize_table

    sched = lookup_schedule(B, L, mc.terminal_embed_size, mc.path_embed_size,
                            mc.encode_size, "f32")
    print(json.dumps({"kernel_schedule": sched.to_dict(),
                      "kernel_strategy": _kern_strategy.label,
                      "autotune_counters": counters_snapshot()}), flush=True)

    def _variant_fwd(impl, table_dtype="f32", quant_tables=None):
        mck = Code2VecConfig(
            terminal_count=mc.terminal_count, path_count=mc.path_count,
            label_count=mc.label_count,
            terminal_embed_size=mc.terminal_embed_size,
            path_embed_size=mc.path_embed_size, encode_size=mc.encode_size,
            dropout_prob=0.25, dtype=DTYPE, embed_grad=EMBED_GRAD,
            use_pallas=impl != "xla", pallas_impl=impl if impl != "xla" else "pool_only",
            pallas_block_b=sched.block_b, pallas_dma_depth=sched.dma_depth,
            pallas_chunk_l=sched.chunk_l, table_dtype=table_dtype,
        )
        mk = Code2Vec(mck)

        @jax.jit
        def f(params, batch):
            logits, _, _ = mk.apply(
                {"params": params}, batch["starts"], batch["paths"],
                batch["ends"], deterministic=True, quant_tables=quant_tables)
            return logits.astype(jnp.float32).sum()

        return f

    for impl in ("pool_only", "gather_split", "fused"):
        bench(f"forward/{impl}", _variant_fwd(impl), state.params, batch)
    _qt = (
        quantize_table(state.params["terminal_embedding"]["embedding"], "int8"),
        quantize_table(state.params["path_embedding"]["embedding"], "int8"),
    )
    bench("forward/fused_int8",
          _variant_fwd("fused", "int8", _qt), state.params, batch)


# --- fwd+bwd, with and without table grads -------------------------------
def loss_fn(params, batch, key):
    logits, _, _ = model.apply(
        {"params": params}, batch["starts"], batch["paths"], batch["ends"],
        deterministic=False, rngs={"dropout": key})
    return weighted_nll(logits.astype(jnp.float32), batch["labels"], cw,
                        batch["example_mask"])


bench("grad", jax.jit(jax.grad(loss_fn)), state.params, batch, key)

# same compute minus the embedding-table backward: zero out the table grads
# by treating the tables as constants (closure capture, not params)
frozen_tables = {
    k: v for k, v in state.params.items()
    if "embedding" in k
}
train_params = {k: v for k, v in state.params.items() if "embedding" not in k}


def loss_frozen(params, batch, key):
    full = dict(params, **frozen_tables)
    logits, _, _ = model.apply(
        {"params": full}, batch["starts"], batch["paths"], batch["ends"],
        deterministic=False, rngs={"dropout": key})
    return weighted_nll(logits.astype(jnp.float32), batch["labels"], cw,
                        batch["example_mask"])


bench("grad_frozen_tables", jax.jit(jax.grad(loss_frozen)), train_params,
      batch, key)


# --- optimizer update alone ----------------------------------------------
grads = jax.jit(jax.grad(loss_fn))(state.params, batch, key)
jax.block_until_ready(grads)


# re-invoked with the SAME state to time the update in isolation;
# donation would poison the caller's buffers
@jax.jit  # jaxlint: disable=JX005
def adam_only(state, grads):
    return state.apply_gradients(grads=grads)


bench("adam", adam_only, state, grads)


# --- full step + production chunk ----------------------------------------
@jax.jit
def one_step(state, batch):
    return raw_train(state, batch)


bench("step", lambda s, b: one_step(s, b)[1], state, batch)

# the touched-rows table optimizer (train/table_opt.py): same step, but the
# table grads never materialize and Adam touches only gathered rows — the
# delta vs "step" is the structural lever's whole-step value
lazy_state = create_train_state(
    tc.with_updates(table_update="lazy"), mc, jax.random.PRNGKey(0),
    jax.tree.map(np.asarray, batch),
)
lazy_raw = build_train_step_fn(mc, cw, table_update="lazy")


@jax.jit
def one_lazy_step(state, batch):
    return lazy_raw(state, batch)


bench("lazy_step", lambda s, b: one_lazy_step(s, b)[1], lazy_state, batch)

runner = EpochRunner(mc, cw, B, L, CHUNK)
run_chunk = runner._train_chunk(CHUNK)
n_valid = CHUNK * B
crows = rng.integers(0, data.n_items, n_valid).astype(np.int32)

trace_dir = os.environ.get("PROF_TRACE_DIR", "").strip()
state2 = create_train_state(tc, mc, jax.random.PRNGKey(0),
                            jax.tree.map(np.asarray, batch))


def chunk_step(state, key):
    key, sub = jax.random.split(key)
    state, loss = run_chunk(state, staged.contexts, staged.row_splits,
                            staged.labels, crows, n_valid, sub)
    return state, loss, key


k = jax.random.PRNGKey(1)
state2, loss, k = chunk_step(state2, k)  # compile
jax.block_until_ready(loss)
if trace_dir:
    jax.profiler.start_trace(trace_dir)
t0 = time.perf_counter()
NCH = 6
for _ in range(NCH):
    state2, loss, k = chunk_step(state2, k)
jax.block_until_ready(loss)
dt = (time.perf_counter() - t0) / (NCH * CHUNK) * 1e3
if trace_dir:
    jax.profiler.stop_trace()
    print(json.dumps({"trace_dir": trace_dir}), flush=True)
results[f"chunk/{CHUNK}"] = dt
print(json.dumps({"component": f"chunk/{CHUNK}", "ms": round(dt, 3)}), flush=True)

# --- attribution summary -------------------------------------------------
table_bwd = results["grad"] - results["grad_frozen_tables"]
print(json.dumps({
    "attribution": {
        "sample": round(results["sample"], 3),
        "fwd": round(results["forward"], 3),
        "bwd_encoder": round(results["grad_frozen_tables"] - results["forward"], 3),
        "bwd_tables(scatter)": round(table_bwd, 3),
        "adam": round(results["adam"], 3),
        "sum_components": round(results["sample"] + results["grad"] + results["adam"], 3),
        "fused_step": round(results["step"], 3),
        "lazy_step": round(results["lazy_step"], 3),
        "chunk_per_step": round(results[f"chunk/{CHUNK}"], 3),
    }
}), flush=True)

print("\n| component | ms |")
print("|---|---|")
for name, ms in results.items():
    print(f"| {name} | {ms:.3f} |")
