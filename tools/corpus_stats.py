"""Context-length histogram + suggested bucket ladder for a corpus file.

The length-aware bucketed batching path (data/pipeline.py, --bucketed)
derives its geometric ladder from the corpus ``row_splits`` histogram at
startup; this tool runs the same derivation OFFLINE so an operator can
inspect the length distribution, see how much of the fixed-``L`` feed is
PAD, and pin an explicit ``--bucket_ladder`` before a long run.

Reads only the corpus text (a lightweight line scan — no vocab files, no
jax, no package import cost beyond the ladder helper), so it works on any
L1-format corpus including ones whose index files live elsewhere. A CSR
container (tools/corpus_convert.py) is even cheaper: the length histogram
comes straight from the container's footer — NO scan of the context
sections at any corpus size.

Usage:
    python tools/corpus_stats.py dataset/corpus.txt --max_contexts 200
    python tools/corpus_stats.py dataset/corpus.csr --max_contexts 200

Prints a per-bucket occupancy table, length percentiles, the pad-efficiency
a fixed-L feed would get vs the suggested ladder, and one final JSON line
(machine-parsable: {"n_methods", "percentiles", "ladder", ...}).
"""

from __future__ import annotations

import argparse
import json
import os
import sys

import numpy as np

_HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, os.path.dirname(_HERE))  # repo root: the package

from code2vec_tpu.data.pipeline import (  # noqa: E402
    assign_buckets,
    derive_bucket_ladder,
    derive_longbag_ladder,
    pad_stats,
    truncated_fraction_of_counts,
)


def context_counts(corpus_path: str) -> np.ndarray:
    """Per-method path-context counts from an L1 corpus file.

    State machine over the record format (SURVEY.md §2.4): a ``paths:``
    line opens the context block; every following line is one context row
    until ``vars:`` or the record-separating blank line closes it. Matches
    the full parsers' row accounting without building any arrays.
    """
    counts: list[int] = []
    n: int | None = None
    with open(corpus_path, encoding="utf-8") as f:
        for raw in f:
            line = raw.rstrip("\n")
            if n is None:
                if line.startswith("paths:"):
                    n = 0
            elif not line or line.startswith("vars:"):
                counts.append(n)
                n = None
            else:
                n += 1
    if n is not None:  # no trailing blank line after the last record
        counts.append(n)
    return np.asarray(counts, np.int64)


def main(argv: list[str] | None = None) -> None:
    parser = argparse.ArgumentParser(
        description="context-length histogram + suggested bucket ladder"
    )
    parser.add_argument("corpus_path", help="L1 corpus.txt")
    parser.add_argument("--max_contexts", type=int, default=200,
                        help="the run's bag size (--max_path_length); the "
                             "ladder tops out here")
    parser.add_argument("--max_buckets", type=int, default=4,
                        help="ladder size cap (= expected step compiles)")
    parser.add_argument("--batch_size", type=int, default=1024,
                        help="batch size for the pad-efficiency estimate")
    parser.add_argument("--chunk_l", type=int, default=128,
                        help="chunk size of the fused kernel's streamed "
                        "softmax — longbag rung widths round up to a "
                        "multiple of it")
    args = parser.parse_args(argv)

    from code2vec_tpu.formats.corpus_io import is_csr_corpus

    if is_csr_corpus(args.corpus_path):
        # the container footer IS the histogram — O(header) read, zero
        # context-section scan at any corpus size
        from code2vec_tpu.formats.corpus_io import read_csr_histogram

        lengths, weights = read_csr_histogram(args.corpus_path)
        counts = np.repeat(lengths, weights)
        print(f"(histogram from CSR container footer: {args.corpus_path})")
    else:
        counts = context_counts(args.corpus_path)
    if not len(counts):
        print(json.dumps({"error": "no records found", "n_methods": 0}))
        return
    ladder = derive_bucket_ladder(
        counts, args.max_contexts, max_buckets=args.max_buckets
    )
    capped = np.minimum(counts, args.max_contexts)
    bucket_of = assign_buckets(capped, ladder)

    pcts = [50, 75, 90, 95, 99]
    percentiles = {
        str(p): int(np.percentile(counts, p)) for p in pcts
    }
    print(f"{len(counts)} methods, context counts "
          f"min={counts.min()} max={counts.max()} mean={counts.mean():.1f}")
    print("percentiles: " + "  ".join(
        f"p{p}={percentiles[str(p)]}" for p in pcts))
    print()
    print(f"{'bucket':>10} {'methods':>10} {'share':>7} {'real/slot':>10}")
    prev = 0
    for b, width in enumerate(ladder):
        members = capped[bucket_of == b]
        share = len(members) / len(counts)
        fill = members.mean() / width if len(members) else 0.0
        print(f"{prev + 1:>4}-{width:<5} {len(members):>10} "
              f"{share:>6.1%} {fill:>9.1%}")
        prev = width

    real, fixed_slots = pad_stats(counts, (args.max_contexts,), args.batch_size)
    _, ladder_slots = pad_stats(counts, ladder, args.batch_size)
    fixed_eff = real / fixed_slots if fixed_slots else 1.0
    ladder_eff = real / ladder_slots if ladder_slots else 1.0
    print()
    print(f"pad efficiency at fixed L={args.max_contexts}: {fixed_eff:.1%}"
          f"  |  bucketed over {list(ladder)}: {ladder_eff:.1%}")

    # truncation accounting: the loss the cap silently takes — every
    # context beyond max_contexts is dropped by the per-epoch subsample,
    # invisible in the loss curves. --max_contexts 0 (longbag rungs) feeds
    # them all; the rung suggestion below is what that run would use.
    trunc = truncated_fraction_of_counts(counts, args.max_contexts)
    lengths, weights = np.unique(counts, return_counts=True)
    longbag = derive_longbag_ladder(
        lengths, weights, args.max_contexts, chunk_l=args.chunk_l
    )
    n_truncated = int((counts > args.max_contexts).sum())
    print(f"truncated at L={args.max_contexts}: {trunc:.2%} of real "
          f"contexts dropped ({n_truncated} methods exceed the cap)")
    if longbag:
        print(f"longbag rungs for --max_contexts 0: {list(longbag)} "
              f"(truncation -> 0)")
    print(f"suggested: --bucketed --bucket_ladder "
          f"{','.join(str(w) for w in ladder)}")
    print(json.dumps({
        "n_methods": int(len(counts)),
        "total_contexts": int(counts.sum()),
        "percentiles": percentiles,
        "ladder": list(ladder),
        "pad_efficiency_fixed": round(fixed_eff, 4),
        "pad_efficiency_bucketed": round(ladder_eff, 4),
        "truncated_context_fraction": round(trunc, 6),
        "longbag_ladder": list(longbag),
    }))


if __name__ == "__main__":
    main()
