"""java-large (BASELINE config 3) end-to-end rehearsal, synthetic.

VERDICT r3 missing-#2: the machinery for 16M methods / 1.3M-path vocab
exists (streaming epochs, host shards, sharded staging, int32 guard) but
had never been exercised at that scale. This tool does, on one host:

  phase gen     — chunked corpus synthesis at --n_methods x ~120 ctx/method
                  into memmap-able .npy files (the fully-vectorized
                  generate_corpus_data would peak ~100 GB in int64
                  temporaries at 1.9G contexts; chunking caps it)
  phase guard   — the staging int32 row_splits guard
                  (train/device_epoch.py) against the REAL total, plus a
                  forced-overflow probe asserting it fires past 2^31
  phase stream  — the bounded-RSS host pipeline: --stream_chunk_items
                  semantics (iter_streaming_batches) driving real train
                  steps on the 1.3M-vocab model, corpus memmap'd from disk
  phase shard   — the device-epoch sharded-staging path
                  (stage_method_corpus_sharded + ShardedEpochRunner) on a
                  --data_axis-device virtual CPU mesh, real train steps,
                  per-device staged bytes reported against the /D budget
                  prediction

Each phase runs in its own subprocess (clean VmHWM attribution; the parent
never imports jax). Results stream as JSON lines; the parent writes a
summary table comparing measured numbers to docs/ARCHITECTURE.md's
memory-budget formulas.

Scale notes vs the real config 3: path/terminal vocabs at 1.3M rows are the
sharded-embedding dimension of the config; labels default to 50k (a
plausible method-name vocab; the head is [100, labels]). The corpus text
layer (29 GB of corpus.txt + a JVM-scale parse) is NOT rehearsed — phases
drive the array-level production paths below it; --host_shard_corpus's
round-robin share is emulated at array level with the same semantics.

Usage:
  python tools/rehearse_java_large.py                  # full 16M rehearsal
  python tools/rehearse_java_large.py --n_methods 2000000 --steps 2
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

_HERE = os.path.dirname(os.path.abspath(__file__))
_REPO = os.path.dirname(_HERE)
sys.path.insert(0, _REPO)

DEFAULT_DIR = "/tmp/java_large_rehearsal"

N_TERMINALS = 1_300_000
N_PATHS = 1_300_000
N_LABELS = 50_000
MEAN_CONTEXTS = 120.0
MAX_CONTEXTS = 1000


def _rss() -> dict:
    """Current and peak RSS in MB from /proc/self/status."""
    out = {}
    with open("/proc/self/status") as f:
        for line in f:
            if line.startswith(("VmRSS", "VmHWM")):
                k, v = line.split(":")
                out[k] = round(int(v.split()[0]) / 1024.0)
    return out


def _emit(**kw) -> None:
    print(json.dumps(kw), flush=True)


# --------------------------------------------------------------------------
# phase: gen
# --------------------------------------------------------------------------

def phase_gen(work_dir: str, n_methods: int) -> None:
    import numpy as np

    os.makedirs(work_dir, exist_ok=True)
    rng = np.random.default_rng(0)
    t0 = time.time()
    counts = np.clip(
        rng.lognormal(np.log(MEAN_CONTEXTS), 0.6, n_methods).astype(np.int64),
        3, MAX_CONTEXTS,
    )
    row_splits = np.zeros(n_methods + 1, np.int64)
    np.cumsum(counts, out=row_splits[1:])
    total = int(row_splits[-1])
    _emit(phase="gen", n_methods=n_methods, total_contexts=total,
          int32_margin=2**31 - total)

    labels = rng.integers(0, N_LABELS, n_methods).astype(np.int32)
    np.save(os.path.join(work_dir, "row_splits.npy"), row_splits)
    np.save(os.path.join(work_dir, "labels.npy"), labels)

    # chunked context synthesis straight into on-disk memmaps: peak host
    # memory stays at the chunk temporaries (~1.5 GB), not ~100 GB
    chunk = 64_000_000
    mms = {
        name: np.lib.format.open_memmap(
            os.path.join(work_dir, f"{name}.npy"), mode="w+",
            dtype=np.int32, shape=(total,),
        )
        for name in ("starts", "paths", "ends")
    }
    lo = 0
    while lo < total:
        hi = min(lo + chunk, total)
        n = hi - lo
        mms["starts"][lo:hi] = rng.integers(1, N_TERMINALS + 1, n, dtype=np.int32)
        mms["paths"][lo:hi] = rng.integers(1, N_PATHS + 1, n, dtype=np.int32)
        mms["ends"][lo:hi] = rng.integers(1, N_TERMINALS + 1, n, dtype=np.int32)
        lo = hi
    for m in mms.values():
        m.flush()
    bytes_csr = total * 3 * 4
    _emit(phase="gen", done=True, seconds=round(time.time() - t0, 1),
          csr_gb=round(bytes_csr / 2**30, 2), **_rss())


# --------------------------------------------------------------------------
# corpus loading shared by the step phases
# --------------------------------------------------------------------------

def _load_corpus_data(work_dir: str, ram: bool = False):
    """CorpusData over the generated context arrays. Default: memmap'd (RSS
    stays page-cache-only until a path materializes rows — the streaming
    phase's bounded-RSS story). ``ram=True`` loads them fully (the staging
    phase gathers billions of random elements; memmap would thrash disk).
    Minimal aux fields: the rehearsal drives training steps, not subtoken
    eval/export."""
    import numpy as np

    mm = None if ram else "r"
    starts = np.load(os.path.join(work_dir, "starts.npy"), mmap_mode=mm)
    paths = np.load(os.path.join(work_dir, "paths.npy"), mmap_mode=mm)
    ends = np.load(os.path.join(work_dir, "ends.npy"), mmap_mode=mm)

    from code2vec_tpu.data.reader import CorpusData
    from code2vec_tpu.data.vocab import Vocab
    row_splits = np.load(os.path.join(work_dir, "row_splits.npy"))
    labels = np.load(os.path.join(work_dir, "labels.npy"))
    n = len(row_splits) - 1

    label_vocab = Vocab()
    for i in range(N_LABELS):
        label_vocab.add_label(f"label{i}")
    terminal_vocab = Vocab()
    terminal_vocab.add("<PAD/>", 0)
    terminal_vocab.add("@question", 1)
    path_vocab = Vocab()
    path_vocab.add("<PAD/>", 0)
    empty: dict = {}
    return CorpusData(
        starts=starts, paths=paths, ends=ends, row_splits=row_splits,
        ids=np.arange(n, dtype=np.int64), labels=labels,
        normalized_labels=[], sources=[None] * n, aliases=[empty] * n,
        terminal_vocab=terminal_vocab, path_vocab=path_vocab,
        label_vocab=label_vocab,
    )


def _model_bits(batch: int, bag: int, table_update: str = "dense"):
    import jax
    import jax.numpy as jnp
    import numpy as np

    from code2vec_tpu.models.code2vec import Code2VecConfig
    from code2vec_tpu.train.config import TrainConfig
    from code2vec_tpu.train.step import create_train_state

    mc = Code2VecConfig(
        terminal_count=N_TERMINALS + 2, path_count=N_PATHS + 1,
        label_count=N_LABELS, terminal_embed_size=100, path_embed_size=100,
        encode_size=100, dropout_prob=0.25, dtype=jnp.float32,
        embed_grad="dense",
    )
    tc = TrainConfig(batch_size=batch, max_path_length=bag,
                     rng_impl="unsafe_rbg", table_update=table_update)
    example = {
        "starts": np.zeros((batch, bag), np.int32),
        "paths": np.zeros((batch, bag), np.int32),
        "ends": np.zeros((batch, bag), np.int32),
        "labels": np.zeros(batch, np.int32),
        "example_mask": np.ones(batch, np.float32),
    }
    state = create_train_state(tc, mc, jax.random.PRNGKey(0), example)
    cw = jnp.ones(mc.label_count, jnp.float32)
    return mc, tc, state, cw


# --------------------------------------------------------------------------
# phase: guard
# --------------------------------------------------------------------------

def phase_guard(work_dir: str) -> None:
    import numpy as np

    from code2vec_tpu.train.device_epoch import stage_method_corpus

    row_splits = np.load(os.path.join(work_dir, "row_splits.npy"))
    total = int(row_splits[-1])
    _emit(phase="guard", total_contexts=total, fits_int32=total < 2**31,
          margin=2**31 - total)

    # forced overflow: a stub corpus whose selected rows exceed 2^31
    # contexts must trip the guard BEFORE any giant allocation happens
    class _Stub:
        pass

    stub = _Stub()
    stub.row_splits = np.array([0, 2**31 + 10], np.int64)
    try:
        stage_method_corpus(stub, np.array([0]), np.random.default_rng(0))
    except ValueError as e:
        _emit(phase="guard", overflow_guard="fired", message=str(e)[:120])
    else:
        _emit(phase="guard", overflow_guard="DID NOT FIRE (BUG)")
        sys.exit(1)


# --------------------------------------------------------------------------
# phase: stream
# --------------------------------------------------------------------------

def phase_stream(work_dir: str, batch: int, bag: int, steps: int,
                 chunk_items: int, table_update: str = "dense") -> None:
    import jax

    jax.config.update("jax_platforms", "cpu")
    import numpy as np

    from code2vec_tpu.data.pipeline import iter_streaming_batches, build_epoch
    from code2vec_tpu.train.step import make_train_step

    data = _load_corpus_data(work_dir)
    _emit(phase="stream", loaded=True, **_rss())
    mc, tc, state, cw = _model_bits(batch, bag, table_update)
    train_step = make_train_step(mc, cw, table_update=table_update)
    rng = np.random.default_rng(0)

    def chunk_builder(idx):
        return build_epoch(data, idx, bag, rng, False)

    idx = np.arange(data.n_items)
    it = iter_streaming_batches(chunk_builder, idx, batch, rng,
                                chunk_items=chunk_items)
    t_start = time.time()
    first_batch_s = None
    times = []
    done = 0
    for b in it:
        if first_batch_s is None:
            first_batch_s = time.time() - t_start  # first chunk build
        t0 = time.time()
        state, loss = train_step(state, b)
        loss.block_until_ready()
        times.append(time.time() - t0)
        done += 1
        if done >= steps:
            break
    _emit(phase="stream", steps=done,
          first_step_s=round(times[0], 1) if times else None,
          later_step_s=round(float(np.mean(times[1:])), 2) if len(times) > 1 else None,
          chunk_items=chunk_items,
          time_to_first_batch_s=round(first_batch_s, 1) if first_batch_s else None,
          final_loss=float(loss), **_rss())


# --------------------------------------------------------------------------
# phase: shard
# --------------------------------------------------------------------------

def phase_shard(work_dir: str, batch: int, bag: int, steps: int,
                data_axis: int, table_update: str = "dense") -> None:
    os.environ["XLA_FLAGS"] = (
        f"--xla_force_host_platform_device_count={data_axis} "
        + os.environ.get("XLA_FLAGS", "")
    )
    import jax

    jax.config.update("jax_platforms", "cpu")
    import numpy as np

    from code2vec_tpu.parallel.mesh import make_mesh
    from code2vec_tpu.parallel.shardings import shard_state
    from code2vec_tpu.train.device_epoch import (
        ShardedEpochRunner,
        stage_method_corpus_sharded,
    )

    data = _load_corpus_data(work_dir, ram=True)
    _emit(phase="shard", loaded=True, **_rss())
    mc, tc, state, cw = _model_bits(batch, bag, table_update)
    mesh = make_mesh(data=data_axis, model=1, ctx=1)
    state = shard_state(mesh, state)
    rng = np.random.default_rng(0)

    t0 = time.time()
    staged = stage_method_corpus_sharded(
        data, np.arange(data.n_items), rng, mesh
    )
    per_device_bytes = int(staged.contexts.shape[1]) * 3 * 4 + (
        int(staged.row_splits.shape[1]) * 4
    )
    _emit(phase="shard", staged=True, seconds=round(time.time() - t0, 1),
          data_axis=data_axis,
          per_device_staged_mb=round(per_device_bytes / 2**20),
          total_staged_mb=round(per_device_bytes * data_axis / 2**20),
          **_rss())

    runner = ShardedEpochRunner(mc, cw, batch, bag, chunk_batches=1,
                                mesh=mesh, table_update=table_update)
    run_chunk = runner._train_chunk(1)
    span = runner.per_shard
    valid = np.ones((runner.n_shards, span), np.float32)
    key = jax.random.PRNGKey(1)
    times = []
    for _ in range(steps):
        rows = rng.integers(
            0, np.maximum(staged.shard_counts[:, None], 1),
            (runner.n_shards, span),
        ).astype(np.int32)
        key, sub = jax.random.split(key)
        t0 = time.time()
        state, loss = run_chunk(
            state, staged.contexts, staged.row_splits, staged.labels,
            rows, valid, sub,
        )
        jax.block_until_ready(loss)
        times.append(time.time() - t0)
    _emit(phase="shard", steps=steps, first_step_s=round(times[0], 1),
          later_step_s=round(float(np.mean(times[1:])), 2) if len(times) > 1 else None,
          final_loss=float(np.asarray(loss).sum()), **_rss())


# --------------------------------------------------------------------------
# phase: hostshard (array-level emulation of --host_shard_corpus's share)
# --------------------------------------------------------------------------

def phase_hostshard(work_dir: str, n_hosts: int) -> None:
    import numpy as np

    row_splits = np.load(os.path.join(work_dir, "row_splits.npy"))
    n = len(row_splits) - 1
    counts = np.diff(row_splits)
    # the reader keeps rows where id % n_hosts == host (data/reader.py
    # round-robin); per-host CSR bytes is the dominant budget term
    shares = []
    for host in range(n_hosts):
        share = int(counts[host::n_hosts].sum()) * 3 * 4
        shares.append(share)
    _emit(phase="hostshard", n_hosts=n_hosts,
          per_host_csr_gb=[round(s / 2**30, 2) for s in shares],
          max_over_min=round(max(shares) / min(shares), 4))


# --------------------------------------------------------------------------
# orchestrator
# --------------------------------------------------------------------------

def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--phase", choices=["gen", "guard", "stream", "shard",
                                        "hostshard"])
    ap.add_argument("--work_dir", default=DEFAULT_DIR)
    ap.add_argument("--n_methods", type=int, default=16_000_000)
    ap.add_argument("--batch", type=int, default=1024)
    ap.add_argument("--bag", type=int, default=200)
    ap.add_argument("--steps", type=int, default=3)
    ap.add_argument("--chunk_items", type=int, default=65_536)
    ap.add_argument("--data_axis", type=int, default=4)
    ap.add_argument("--n_hosts", type=int, default=8)
    ap.add_argument("--table_update", choices=("dense", "lazy"),
                    default="dense",
                    help="embedding-table optimizer for the train phases — "
                    "'lazy' (touched-rows, train/table_opt.py) is the mode "
                    "built for exactly this vocab scale, where the dense "
                    "full-table Adam RMW grows with the 16M-row vocab")
    ap.add_argument("--keep", action="store_true",
                    help="keep the generated corpus files")
    args = ap.parse_args()

    if args.phase == "gen":
        return phase_gen(args.work_dir, args.n_methods)
    if args.phase == "guard":
        return phase_guard(args.work_dir)
    if args.phase == "stream":
        return phase_stream(args.work_dir, args.batch, args.bag, args.steps,
                            args.chunk_items, args.table_update)
    if args.phase == "shard":
        return phase_shard(args.work_dir, args.batch, args.bag, args.steps,
                           args.data_axis, args.table_update)
    if args.phase == "hostshard":
        return phase_hostshard(args.work_dir, args.n_hosts)

    # parent: run every phase in its own subprocess, streaming output
    t0 = time.time()
    # forward the recipe shape too — the train phases read batch/bag, and
    # silently running the defaults would make a small-scale invocation
    # lie about what it exercised
    shape = ["--batch", str(args.batch), "--bag", str(args.bag),
             "--table_update", args.table_update]
    phases = [
        ["--phase", "gen", "--n_methods", str(args.n_methods)],
        ["--phase", "guard"],
        ["--phase", "hostshard", "--n_hosts", str(args.n_hosts)],
        ["--phase", "stream", "--steps", str(args.steps),
         "--chunk_items", str(args.chunk_items)] + shape,
        ["--phase", "shard", "--steps", str(args.steps),
         "--data_axis", str(args.data_axis)] + shape,
    ]
    for extra in phases:
        cmd = [sys.executable, os.path.abspath(__file__),
               "--work_dir", args.work_dir] + extra
        _emit(running=extra[1])
        rc = subprocess.call(cmd)
        if rc != 0:
            _emit(phase=extra[1], rc=rc, error="phase failed")
            sys.exit(rc)
    if not args.keep:
        import shutil

        shutil.rmtree(args.work_dir, ignore_errors=True)
    _emit(done=True, total_minutes=round((time.time() - t0) / 60.0, 1))


if __name__ == "__main__":
    main()
