"""One-shot TPU ablation over the flagship device-epoch path: embed_grad x
rng_impl x dtype, pallas vs XLA attention at two bag sizes, and chunk
length. Prints one JSON line per measurement plus a final markdown table
(for docs/ARCHITECTURE.md). Designed to survive a flaky TPU tunnel: each
measurement is independent, results stream as they land, and a crash still
leaves the lines printed so far.

Usage: python tools/run_tpu_ablation.py [--quick]
"""

from __future__ import annotations

import argparse
import json
import sys
import time

import numpy as np

sys.path.insert(0, ".")


def measure_step(
    jax,
    embed_grad: str,
    rng_impl: str,
    dtype_name: str,
    use_pallas: bool = False,
    pallas_block_b: int = 8,
    attn_impl: str = "xla",
    encoder_impl: str = "concat",
    sample_prefetch: bool = False,
    batch: int = 1024,
    bag: int = 200,
    chunk: int = 16,
    steps: int = 48,
    adam_mu_dtype: str = "float32",
    table_update: str = "dense",
    embed: int = 100,
    encode: int = 100,
    n_methods: int | None = None,
    mean_contexts: float = 120.0,
    max_contexts: int = 400,
) -> float:
    """ms/step on the EpochRunner scanned-chunk path (what bench.py runs)."""
    import jax.numpy as jnp

    from code2vec_tpu.data.synth import SynthSpec, corpus_data_from_raw, generate_corpus_data
    from code2vec_tpu.models.code2vec import Code2VecConfig
    from code2vec_tpu.train.config import TrainConfig
    from code2vec_tpu.train.device_epoch import EpochRunner, stage_method_corpus
    from code2vec_tpu.train.step import create_train_state

    spec = SynthSpec(
        n_methods=n_methods if n_methods is not None else max(batch * 8, 8192),
        n_terminals=360_631,
        n_paths=342_845,
        n_labels=8_000,
        mean_contexts=mean_contexts,
        max_contexts=max_contexts,
        seed=0,
    )
    data = corpus_data_from_raw(generate_corpus_data(spec))
    model_config = Code2VecConfig(
        terminal_count=spec.n_terminals + 2,
        path_count=spec.n_paths + 1,
        label_count=len(data.label_vocab),
        terminal_embed_size=embed,
        path_embed_size=embed,
        encode_size=encode,
        dropout_prob=0.25,
        dtype=jnp.bfloat16 if dtype_name == "bf16" else jnp.float32,
        embed_grad=embed_grad,
        use_pallas=use_pallas,
        pallas_block_b=pallas_block_b,
        attn_impl=attn_impl,
        encoder_impl=encoder_impl,
    )
    config = TrainConfig(
        batch_size=batch, max_path_length=bag, rng_impl=rng_impl,
        adam_mu_dtype=adam_mu_dtype, table_update=table_update,
    )
    rng = np.random.default_rng(0)
    example = {
        "starts": np.zeros((batch, bag), np.int32),
        "paths": np.zeros((batch, bag), np.int32),
        "ends": np.zeros((batch, bag), np.int32),
        "labels": np.zeros(batch, np.int32),
        "example_mask": np.ones(batch, np.float32),
    }
    state = create_train_state(config, model_config, jax.random.PRNGKey(0), example)
    cw = jnp.ones(model_config.label_count, jnp.float32)
    runner = EpochRunner(model_config, cw, batch, bag, chunk,
                         sample_prefetch=sample_prefetch,
                         table_update=table_update)
    staged = stage_method_corpus(data, np.arange(data.n_items), rng)
    run_chunk = runner._train_chunk(chunk)
    n_valid = chunk * batch

    key = jax.random.PRNGKey(1)

    def run(state, key):
        rows = rng.integers(0, data.n_items, n_valid).astype(np.int32)
        key, sub = jax.random.split(key)
        state, loss = run_chunk(
            state, staged.contexts, staged.row_splits, staged.labels,
            rows, n_valid, sub,
        )
        return state, loss, key

    for _ in range(2):  # compile + warm
        state, loss, key = run(state, key)
    jax.block_until_ready(loss)

    n_chunks = -(-steps // chunk)
    t0 = time.perf_counter()
    for _ in range(n_chunks):
        state, loss, key = run(state, key)
    jax.block_until_ready(loss)
    return (time.perf_counter() - t0) / (n_chunks * chunk) * 1e3


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true", help="fewer configs")
    ap.add_argument(
        "--r4",
        action="store_true",
        help="round-4 focused matrix: winner recipe x2 repeats, mu-bf16 A/B "
        "x2, wide-model (512/512) f32 vs bf16 x2 — bounds the ~3%% "
        "run-to-run noise band on the round-3 single-measurement claims",
    )
    ap.add_argument(
        "--attn-ab",
        action="store_true",
        help="the lowering matrix on the current winner recipe: attention "
        "{xla, streaming} x encoder {concat, split} once each, then the "
        "two fastest combos re-measured — the focused follow-up for a "
        "short tunnel window after the full --r4 matrix was captured",
    )
    ap.add_argument(
        "--r5",
        action="store_true",
        help="the table-optimizer A/B on the winner recipe: dense vs lazy "
        "(touched-rows SparseAdam, train/table_opt.py) x2 repeats each — "
        "the structural lever for the full-table grad + Adam RMW traffic "
        "(VERDICT r4 next-#2); plus lazy at a long-bag shape where the "
        "touched-rows/vocab ratio is smaller",
    )
    args = ap.parse_args()

    import os

    import jax

    if os.environ.get("JAX_PLATFORMS", "").strip():
        # the axon plugin pre-empts the env var; re-assert via config API
        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"].strip())

    backend = jax.default_backend()
    print(json.dumps({"backend": backend}), flush=True)

    results: list[dict] = []

    def record(name: str, **kw):
        try:
            ms = measure_step(jax, **kw)
        except Exception as e:  # noqa: BLE001 - stream what we have
            print(json.dumps({"config": name, "error": str(e)[:300]}), flush=True)
            return
        ctx_s = kw.get("batch", 1024) * kw.get("bag", 200) / ms * 1e3
        row = {"config": name, **kw, "ms_per_step": round(ms, 3),
               "contexts_per_sec": round(ctx_s, 0)}
        results.append(row)
        print(json.dumps(row), flush=True)

    def print_table():
        print("\n| config | ms/step | contexts/sec |")
        print("|---|---|---|")
        for r in sorted(results, key=lambda r: r["ms_per_step"]):
            print(f"| {r['config']} | {r['ms_per_step']} | {int(r['contexts_per_sec']):,} |")

    if args.attn_ab:
        base = dict(embed_grad="dense", rng_impl="unsafe_rbg",
                    dtype_name="f32", adam_mu_dtype="bfloat16")
        combos = [
            (a, e) for a in ("xla", "streaming") for e in ("concat", "split")
        ]
        for a, e in combos:
            record(f"mu-bf16/attn-{a}/enc-{e} #1",
                   attn_impl=a, encoder_impl=e, **base)
        # second measurement for the two fastest combos: bounds the noise
        # on exactly the rows a default flip would rest on
        for row in sorted(results, key=lambda r: r["ms_per_step"])[:2]:
            record(row["config"].replace("#1", "#2"),
                   attn_impl=row["attn_impl"],
                   encoder_impl=row["encoder_impl"], **base)
        # double-buffered sampling on the winning combo (x2): overlaps the
        # sampling gathers with the step (train/device_epoch.py)
        best = min(results, key=lambda r: r["ms_per_step"]) if results else None
        for rep in (1, 2) if best is not None else ():
            record(best["config"].split(" #")[0] + f"/prefetch #{rep}",
                   attn_impl=best["attn_impl"],
                   encoder_impl=best["encoder_impl"],
                   sample_prefetch=True, **base)
        print_table()
        return

    if args.r5:
        base = dict(embed_grad="dense", rng_impl="unsafe_rbg",
                    dtype_name="f32", adam_mu_dtype="bfloat16")
        for rep in (1, 2):
            record(f"mu-bf16/table-dense #{rep}", table_update="dense", **base)
        for rep in (1, 2):
            record(f"mu-bf16/table-lazy #{rep}", table_update="lazy", **base)
        # long-bag point: batch 256 x bag 1024 touches <=0.72M slots
        # against the same 703k-row vocabs — the regime where touched-rows
        # wins grow (and the java-large-vocab proxy)
        for mode in ("dense", "lazy"):
            record(f"b256/bag1024/table-{mode}", table_update=mode,
                   batch=256, bag=1024, chunk=8,
                   mean_contexts=819.2, max_contexts=2048, **base)
        print_table()
        return

    if args.r4:
        # winner recipe (round-3 ablation): dense/unsafe_rbg/f32 — two
        # repeats re-confirm the 25.3 ms claim and bound the noise
        for rep in (1, 2):
            record(f"dense/unsafe_rbg/f32 #{rep}",
                   embed_grad="dense", rng_impl="unsafe_rbg", dtype_name="f32")
        # the staged HBM lever: bf16 Adam first moment (~280 MB/step less RMW)
        for rep in (1, 2):
            record(f"dense/unsafe_rbg/f32/mu-bf16 #{rep}",
                   embed_grad="dense", rng_impl="unsafe_rbg", dtype_name="f32",
                   adam_mu_dtype="bfloat16")
        # wide model (BASELINE config 4: 512/512): the dtype-regime-flip
        # claim (bf16 wins wide) gets its second measurement, both arms
        for rep in (1, 2):
            record(f"wide512/f32 #{rep}",
                   embed_grad="dense", rng_impl="unsafe_rbg", dtype_name="f32",
                   embed=512, encode=512)
            record(f"wide512/bf16 #{rep}",
                   embed_grad="dense", rng_impl="unsafe_rbg", dtype_name="bf16",
                   embed=512, encode=512)
        # streaming-softmax pool lowering A/B on the winner recipe: the
        # isolated pool fwd+bwd measured faster than jax.nn.softmax's chain
        # (bench_ctx pool rows, 2.7 vs 3.8 ms at B1024/bag200) — does it
        # survive fusion into the full step?
        for rep in (1, 2):
            record(f"dense/unsafe_rbg/f32/mu-bf16/attn-streaming #{rep}",
                   embed_grad="dense", rng_impl="unsafe_rbg", dtype_name="f32",
                   adam_mu_dtype="bfloat16", attn_impl="streaming")
        print_table()
        return

    # --- embed_grad x rng_impl (bf16, the production recipe) -------------
    grads = ["dense", "segment", "segment_sorted"]
    rngs = ["threefry2x32", "unsafe_rbg"] if not args.quick else ["threefry2x32"]
    for eg in grads:
        for ri in rngs:
            record(f"{eg}/{ri}/bf16", embed_grad=eg, rng_impl=ri,
                   dtype_name="bf16")

    # --- dtype check on the winner-so-far --------------------------------
    best = min(results, key=lambda r: r["ms_per_step"]) if results else None
    if best is not None:
        record(
            f"{best['embed_grad']}/{best['rng_impl']}/f32",
            embed_grad=best["embed_grad"], rng_impl=best["rng_impl"],
            dtype_name="f32",
        )
        # bf16 first-moment storage: does trimming the mu read-modify-write
        # (~280 MB/step at top11 scale) show up end-to-end?
        record(
            f"{best['embed_grad']}/{best['rng_impl']}/f32/mu-bf16",
            embed_grad=best["embed_grad"], rng_impl=best["rng_impl"],
            dtype_name="f32", adam_mu_dtype="bfloat16",
        )

    # --- pallas vs XLA attention at two bag sizes + block_b tuning -------
    for bag, batch in ((200, 1024), (1024, 256)):
        record(
            f"attn:xla/bag{bag}",
            embed_grad="dense", rng_impl="threefry2x32",
            dtype_name="bf16", bag=bag, batch=batch,
        )
        blocks = (8,) if args.quick else (8, 16, 32)
        for block_b in blocks:
            record(
                f"attn:pallas-b{block_b}/bag{bag}",
                embed_grad="dense", rng_impl="threefry2x32",
                dtype_name="bf16", use_pallas=True, pallas_block_b=block_b,
                bag=bag, batch=batch,
            )

    # --- chunk length ----------------------------------------------------
    if not args.quick:
        for chunk in (8, 32):
            record(
                f"chunk{chunk}", embed_grad="dense", rng_impl="threefry2x32",
                dtype_name="bf16", chunk=chunk,
            )

    print_table()


if __name__ == "__main__":
    main()
