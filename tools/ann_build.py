"""Train and save an IVF-PQ ANN index from an exported ``code.vec``.

The offline half of the ``--retrieval_backend ann`` serving path
(serve/retrieval.py): read the exported code vectors, train the coarse
k-means quantizer + per-subspace PQ codebooks (seeded-deterministic —
same seed, same container bytes), lay the codes out cell-major, and write
the versioned mmap-loadable container (formats/ann_io.py) with the
serving defaults (``n_probe``/``shortlist``) baked into its header::

    python tools/ann_build.py --code_vec out/code.vec --out out/ann.index \\
        --n_list 256 --m 8 --n_probe 8 --shortlist 128

Prints one JSON summary line (geometry, pad efficiency of the cell-major
layout, build seconds, container bytes). ``--n_list 0`` (default) picks
~sqrt(N) rounded to a multiple of 8.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

_HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, os.path.dirname(_HERE))  # repo root: the package


def build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(
        description="IVF-PQ ANN index builder (see module docstring)"
    )
    parser.add_argument("--code_vec", required=True,
                        help="exported code.vec (word2vec text format)")
    parser.add_argument("--out", required=True,
                        help="output container path (e.g. out/ann.index)")
    parser.add_argument("--n_list", type=int, default=0,
                        help="coarse cells; 0 = ~sqrt(N) rounded to 8")
    parser.add_argument("--m", type=int, default=8,
                        help="PQ subspaces (must divide the vector dim)")
    parser.add_argument("--kmeans_iters", type=int, default=25)
    parser.add_argument("--pq_iters", type=int, default=15)
    parser.add_argument("--batch_size", type=int, default=16384,
                        help="mini-batch rows per Lloyd's iteration")
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument("--n_probe", type=int, default=8,
                        help="serving default baked into the container")
    parser.add_argument("--shortlist", type=int, default=128,
                        help="serving default baked into the container")
    parser.add_argument("--accelerator", action="store_true", default=False,
                        help="train on the default device backend; off = "
                        "pin CPU (same contract as the serve CLI)")
    return parser


def main(argv: list[str] | None = None) -> int:
    args = build_parser().parse_args(argv)

    from code2vec_tpu.cli import pin_platform

    pin_platform(not args.accelerator)

    from code2vec_tpu.ann.index import build_index, save_index
    from code2vec_tpu.formats.vectors_io import read_code_vectors

    labels, rows = read_code_vectors(args.code_vec)
    n = len(labels)
    if n < 2:
        print(f"ann_build: {args.code_vec} holds {n} vectors; need >= 2",
              file=sys.stderr)
        return 2
    n_list = args.n_list
    if n_list <= 0:
        n_list = max(-(-int(round(n ** 0.5)) // 8) * 8, 8)
    m = args.m
    dim = rows.shape[1]
    if dim % m:
        divisors = [d for d in range(m, 0, -1) if dim % d == 0]
        m = divisors[0]
        print(
            f"ann_build: --m {args.m} does not divide dim {dim}; using "
            f"m={m}",
            file=sys.stderr,
        )

    t0 = time.perf_counter()
    index, unit = build_index(
        rows, n_list=n_list, m=m, seed=args.seed,
        kmeans_iters=args.kmeans_iters, pq_iters=args.pq_iters,
        batch_size=args.batch_size,
    )
    build_seconds = time.perf_counter() - t0
    save_index(
        args.out, index, unit, labels,
        defaults={"n_probe": args.n_probe, "shortlist": args.shortlist},
    )

    meta = index.meta
    slots = meta["n_list"] * meta["capacity"]
    print(
        json.dumps(
            {
                "out": args.out,
                "n": meta["n"],
                "dim": meta["dim"],
                "n_list": meta["n_list"],
                "m": meta["m"],
                "capacity": meta["capacity"],
                "cell_pad_efficiency": round(meta["n"] / slots, 4),
                "n_probe": args.n_probe,
                "shortlist": args.shortlist,
                "seed": args.seed,
                "build_seconds": round(build_seconds, 2),
                "container_bytes": os.path.getsize(args.out),
            }
        ),
        flush=True,
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
