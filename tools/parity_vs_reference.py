"""Head-to-head parity run: the actual reference implementation (torch CPU)
vs this framework, on the SAME synthetic corpus through the SAME artifact
files. Demonstrates (1) artifact-format interop — the reference's
DatasetReader consumes our writers' output unmodified — and (2) F1 parity
on an identical recipe.

Usage: python tools/parity_vs_reference.py [--reference /root/reference]
Prints one JSON line: both F1 trajectories and bests.

Notes: --eval_method exact (the reference's subtoken evaluator crashes on
current numpy — `int.item()` in main.py:subtoken_match — an upstream bug,
not a format issue). The reference's train/test split is unseeded
(SURVEY §2.6), so trajectories are comparable, not identical.
"""

from __future__ import annotations

import argparse
import json
import os
import re
import subprocess
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def run_reference(ref_dir: str, paths: dict, out_dir: str, epochs: int) -> list[float]:
    result = subprocess.run(
        [
            sys.executable, "main.py",
            "--corpus_path", str(paths["corpus"]),
            "--path_idx_path", str(paths["path_idx"]),
            "--terminal_idx_path", str(paths["terminal_idx"]),
            "--batch_size", "64", "--encode_size", "100",
            "--max_epoch", str(epochs), "--no_cuda",
            "--eval_method", "exact",
            "--model_path", out_dir,
            "--vectors_path", os.path.join(out_dir, "code.vec"),
        ],
        cwd=ref_dir,
        capture_output=True,
        text=True,
        timeout=3600,
    )
    f1s = [
        float(m.group(1))
        for m in re.finditer(
            r'\{"metric": "f1", "value": ([0-9.eE+-]+)\}', result.stdout + result.stderr
        )
    ]
    # a partial trajectory from a crashed run would be a misleading parity
    # claim — demand a clean exit AND all epochs (the reference's early
    # stop needs bad_count > 10, unreachable at the epoch counts used here)
    if result.returncode != 0 or len(f1s) < min(epochs, 11):
        print(result.stdout[-2000:], file=sys.stderr)
        print(result.stderr[-2000:], file=sys.stderr)
        raise RuntimeError(
            f"reference run incomplete: rc={result.returncode}, "
            f"{len(f1s)}/{epochs} epoch metrics"
        )
    return f1s


def run_ours(paths: dict, epochs: int) -> list[float]:
    import jax

    jax.config.update("jax_platforms", "cpu")

    from code2vec_tpu.data.reader import load_corpus
    from code2vec_tpu.train.config import TrainConfig
    from code2vec_tpu.train.loop import train

    data = load_corpus(
        paths["corpus"], paths["path_idx"], paths["terminal_idx"], cache=False
    )
    config = TrainConfig(
        batch_size=64,
        encode_size=100,
        max_epoch=epochs,
        eval_method="exact",
        print_sample_cycle=0,
    )
    result = train(config, data)
    return [h["f1"] for h in result.history]


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--reference", default="/root/reference")
    ap.add_argument("--epochs", type=int, default=6)
    args = ap.parse_args()

    from code2vec_tpu.data.synth import SPECS, generate_corpus_files

    with tempfile.TemporaryDirectory() as tmp:
        paths = generate_corpus_files(tmp, SPECS["small"])
        ref_out = os.path.join(tmp, "ref_out")
        os.makedirs(ref_out)
        ref_f1 = run_reference(args.reference, paths, ref_out, args.epochs)
        ours_f1 = run_ours(paths, args.epochs)

    print(
        json.dumps(
            {
                "corpus": "synth small (2000 methods), identical artifact files",
                "eval_method": "exact",
                "reference_f1": [round(v, 4) for v in ref_f1],
                "ours_f1": [round(v, 4) for v in ours_f1],
                "reference_best": round(max(ref_f1), 4),
                "ours_best": round(max(ours_f1), 4),
            }
        )
    )


if __name__ == "__main__":
    main()
