"""Head-to-head parity run: the actual reference implementation (torch CPU)
vs this framework, on the SAME synthetic corpus through the SAME artifact
files. Demonstrates (1) artifact-format interop — the reference's
DatasetReader consumes our writers' output unmodified — and (2) F1 parity
on an identical recipe.

Usage: python tools/parity_vs_reference.py [--reference /root/reference]
Prints one JSON line: both F1 trajectories and bests.

Default --eval_method subtoken: the BASELINE headline metric. The
reference's own subtoken evaluator crashes on current numpy (`int.item()`
in main.py:339-359 — `tolist()` yields python ints on modern numpy, which
have no `.item()`; an upstream bug, not a format issue), so the reference
subprocess runs through a driver that monkeypatches `subtoken_match` /
`averaged_subtoken_match` to re-wrap their inputs in a list whose
`tolist()` yields numpy scalars — the same shim
tests/test_metrics_vs_reference.py uses; the reference's metric code
itself runs unmodified. The reference's train/test split is unseeded
(SURVEY §2.6), so trajectories are comparable, not identical.
"""

from __future__ import annotations

import argparse
import json
import os
import re
import subprocess
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# Driver for the reference subprocess. Imports the reference's main.py with
# patched argv (it parses flags at import), installs the tolist shim around
# the subtoken evaluators, then calls its main(). Formatted with
# (ref_dir, json-encoded argv list).
_REF_DRIVER = """\
import sys

sys.path.insert(0, {ref_dir!r})
sys.argv = {argv}

import numpy as np

import main as ref_main


class _NumpyScalarList(list):
    \"\"\"tolist() -> numpy scalars, so the reference's ``x.item()`` works
    on numpy versions where plain-array tolist() yields python ints.\"\"\"

    def tolist(self):
        return [np.int64(x) for x in self]


def _shimmed(fn):
    def wrapper(expected_labels, actual_labels, label_vocab):
        return fn(
            _NumpyScalarList(int(x) for x in np.asarray(expected_labels).ravel()),
            _NumpyScalarList(int(x) for x in np.asarray(actual_labels).ravel()),
            label_vocab,
        )

    return wrapper


ref_main.subtoken_match = _shimmed(ref_main.subtoken_match)
ref_main.averaged_subtoken_match = _shimmed(ref_main.averaged_subtoken_match)
ref_main.main()
"""


def run_reference(
    ref_dir: str, paths: dict, out_dir: str, epochs: int, eval_method: str
) -> list[float]:
    argv = [
        "main.py",
        "--corpus_path", str(paths["corpus"]),
        "--path_idx_path", str(paths["path_idx"]),
        "--terminal_idx_path", str(paths["terminal_idx"]),
        "--batch_size", "64", "--encode_size", "100",
        "--max_epoch", str(epochs), "--no_cuda",
        "--eval_method", eval_method,
        "--model_path", out_dir,
        "--vectors_path", os.path.join(out_dir, "code.vec"),
    ]
    driver = os.path.join(out_dir, "_ref_driver.py")
    with open(driver, "w") as f:
        f.write(_REF_DRIVER.format(ref_dir=ref_dir, argv=json.dumps(argv)))
    result = subprocess.run(
        [sys.executable, driver],
        cwd=ref_dir,
        capture_output=True,
        text=True,
        timeout=3600,
    )
    f1s = [
        float(m.group(1))
        for m in re.finditer(
            r'\{"metric": "f1", "value": ([0-9.eE+-]+)\}', result.stdout + result.stderr
        )
    ]
    # a partial trajectory from a crashed run would be a misleading parity
    # claim — demand a clean exit AND all epochs (the reference's early
    # stop needs bad_count > 10, unreachable at the epoch counts used here)
    if result.returncode != 0 or len(f1s) < min(epochs, 11):
        print(result.stdout[-2000:], file=sys.stderr)
        print(result.stderr[-2000:], file=sys.stderr)
        raise RuntimeError(
            f"reference run incomplete: rc={result.returncode}, "
            f"{len(f1s)}/{epochs} epoch metrics"
        )
    return f1s


def run_ours(paths: dict, epochs: int, eval_method: str) -> list[float]:
    import jax

    jax.config.update("jax_platforms", "cpu")

    from code2vec_tpu.data.reader import load_corpus
    from code2vec_tpu.train.config import TrainConfig
    from code2vec_tpu.train.loop import train

    data = load_corpus(
        paths["corpus"], paths["path_idx"], paths["terminal_idx"], cache=False
    )
    config = TrainConfig(
        batch_size=64,
        encode_size=100,
        max_epoch=epochs,
        eval_method=eval_method,
        print_sample_cycle=0,
    )
    result = train(config, data)
    return [h["f1"] for h in result.history]


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--reference", default="/root/reference")
    ap.add_argument("--epochs", type=int, default=6)
    ap.add_argument(
        "--eval_method",
        default="subtoken",
        choices=["exact", "subtoken", "ave_subtoken"],
        help="subtoken (default) is the BASELINE headline metric",
    )
    ap.add_argument(
        "--spec", default="small",
        help="synth corpus spec (code2vec_tpu.data.synth.SPECS); "
        "'parity10k' is the discriminating operating point — both sides "
        "land mid-range F1, so 'matching' actually means something",
    )
    ap.add_argument(
        "--ref_runs", type=int, default=1,
        help="reference repetitions: its train/test split is unseeded "
        "(SURVEY §2.6), so the spread across runs bounds its variance; "
        "ours is seeded and runs once",
    )
    ap.add_argument(
        "--ours_only", action="store_true",
        help="calibration mode: run only this framework's side",
    )
    args = ap.parse_args()

    from code2vec_tpu.data.synth import SPECS, generate_corpus_files

    with tempfile.TemporaryDirectory() as tmp:
        paths = generate_corpus_files(tmp, SPECS[args.spec])
        ref_runs: list[list[float]] = []
        if not args.ours_only:
            for rep in range(args.ref_runs):
                ref_out = os.path.join(tmp, f"ref_out{rep}")
                os.makedirs(ref_out)
                ref_runs.append(run_reference(
                    args.reference, paths, ref_out, args.epochs,
                    args.eval_method,
                ))
                print(json.dumps({
                    "ref_run": rep,
                    "f1": [round(v, 4) for v in ref_runs[-1]],
                    "best": round(max(ref_runs[-1]), 4),
                }), flush=True)
        ours_f1 = run_ours(paths, args.epochs, args.eval_method)

    bests = [max(r) for r in ref_runs]
    out = {
        "corpus": f"synth {args.spec} "
        f"({SPECS[args.spec].n_methods} methods), identical artifact files",
        "eval_method": args.eval_method,
        "ours_f1": [round(v, 4) for v in ours_f1],
        "ours_best": round(max(ours_f1), 4),
    }
    if ref_runs:
        out.update(
            reference_runs=[[round(v, 4) for v in r] for r in ref_runs],
            reference_bests=[round(b, 4) for b in bests],
            reference_best_mean=round(sum(bests) / len(bests), 4),
            reference_best_min=round(min(bests), 4),
            reference_best_max=round(max(bests), 4),
        )
    print(json.dumps(out))


if __name__ == "__main__":
    main()
