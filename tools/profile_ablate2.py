"""Real-step variants: custom-vjp embedding backward + rbg dropout RNG."""

import time
from functools import partial

import jax

jax.config.update("jax_compilation_cache_dir", "/tmp/jaxcache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
import jax.numpy as jnp
import numpy as np
import optax

from code2vec_tpu.train.step import weighted_nll, torch_style_adam

B, L, DIM, ENC = 1024, 200, 100, 100
VT, VP, C = 360_633, 342_846, 8_000

rng = np.random.default_rng(0)
batch = {
    "starts": jax.device_put(rng.integers(1, VT, (B, L)).astype(np.int32)),
    "paths": jax.device_put(rng.integers(1, VP, (B, L)).astype(np.int32)),
    "ends": jax.device_put(rng.integers(1, VT, (B, L)).astype(np.int32)),
    "labels": jax.device_put(rng.integers(0, C, B).astype(np.int32)),
    "example_mask": jax.device_put(np.ones(B, np.float32)),
}
cw = jnp.ones(C, jnp.float32)


def init_params(key):
    k = jax.random.split(key, 5)
    return {
        "T": jax.random.normal(k[0], (VT, DIM), jnp.float32),
        "P": jax.random.normal(k[1], (VP, DIM), jnp.float32),
        "W": jax.random.normal(k[2], (3 * DIM, ENC), jnp.float32) * 0.05,
        "ln_scale": jnp.ones(ENC, jnp.float32),
        "ln_bias": jnp.zeros(ENC, jnp.float32),
        "a": jax.random.normal(k[3], (ENC,), jnp.float32) * 0.1,
        "head_w": jax.random.normal(k[4], (ENC, C), jnp.float32) * 0.05,
        "head_b": jnp.zeros(C, jnp.float32),
    }


# ---- embedding lookup variants ------------------------------------------

def take_embed(table, ids):
    return table[ids].astype(jnp.bfloat16)


@partial(jax.custom_vjp, nondiff_argnums=(2,))
def sorted_embed(table, ids, grad_mode):
    return table[ids].astype(jnp.bfloat16)


def _se_fwd(table, ids, grad_mode):
    return table[ids].astype(jnp.bfloat16), (ids, table.shape[0])


def _se_bwd(grad_mode, res, g):
    ids, V = res
    flat_ids = ids.reshape(-1)
    gf = g.reshape(-1, g.shape[-1])
    if "f32" in grad_mode:
        gf = gf.astype(jnp.float32)
    if "sort" in grad_mode:
        order = jnp.argsort(flat_ids)
        dt = jax.ops.segment_sum(
            gf[order], flat_ids[order], num_segments=V, indices_are_sorted=True
        )
    else:
        dt = jax.ops.segment_sum(gf, flat_ids, num_segments=V)
    return dt.astype(jnp.float32), None


sorted_embed.defvjp(_se_fwd, _se_bwd)


def model_apply(params, batch, dropout_key, embed_fn, deterministic=False):
    es = embed_fn(params["T"], batch["starts"])
    ep = embed_fn(params["P"], batch["paths"])
    ee = embed_fn(params["T"], batch["ends"])
    x = jnp.concatenate([es, ep, ee], axis=-1)  # [B, L, 3*DIM] bf16
    h = x @ params["W"].astype(jnp.bfloat16)  # [B, L, ENC]
    h32 = h.astype(jnp.float32)
    mean = h32.mean(-1, keepdims=True)
    var = h32.var(-1, keepdims=True)
    h32 = (h32 - mean) * jax.lax.rsqrt(var + 1e-6) * params["ln_scale"] + params["ln_bias"]
    h = jnp.tanh(h32).astype(jnp.bfloat16)
    if not deterministic:
        keep = jax.random.bernoulli(dropout_key, 0.75, h.shape)
        h = jnp.where(keep, h / 0.75, 0).astype(jnp.bfloat16)
    scores = (h @ params["a"].astype(jnp.bfloat16)).astype(jnp.float32)  # [B, L]
    mask = (batch["starts"] != 0).astype(jnp.float32)
    scores = jnp.where(mask > 0, scores, -3.4e38)
    attn = jax.nn.softmax(scores, axis=-1)
    code = jnp.einsum("bl,bld->bd", attn.astype(jnp.bfloat16), h)  # [B, ENC]
    logits = code.astype(jnp.float32) @ params["head_w"] + params["head_b"]
    return logits


def bench(name, embed_fn, impl="threefry", n_scan=10, reps=6):
    params = init_params(jax.random.PRNGKey(0))
    tx = torch_style_adam(0.01, 0.9, 0.999, 0.0)
    opt = tx.init(params)
    key = jax.random.key(1, impl=impl)

    def loss_fn(p, batch, dk):
        logits = model_apply(p, batch, dk, embed_fn)
        return weighted_nll(logits, batch["labels"], cw, batch["example_mask"])

    @partial(jax.jit, donate_argnums=0)
    def chunk(carry, batch):
        params, opt, key = carry
        def step(c, _):
            params, opt, key = c
            key, dk = jax.random.split(key)
            loss, grads = jax.value_and_grad(loss_fn)(params, batch, dk)
            upd, opt = tx.update(grads, opt, params)
            params = optax.apply_updates(params, upd)
            return (params, opt, key), loss
        (params, opt, key), losses = jax.lax.scan(step, (params, opt, key), None, length=n_scan)
        return (params, opt, key), losses.sum()

    print(f"{name}: compiling...", flush=True)
    t0 = time.perf_counter()
    carry = (params, opt, key)
    carry, l = chunk(carry, batch)
    jax.block_until_ready(l)
    print(f"{name}: compile+first {time.perf_counter() - t0:.1f}s", flush=True)
    t0 = time.perf_counter()
    for _ in range(reps):
        carry, l = chunk(carry, batch)
    jax.block_until_ready(l)
    print(f"{name:46s} {(time.perf_counter() - t0) / (reps * n_scan) * 1e3:8.3f} ms/step  loss={float(l)/n_scan:.4f}")


bench("inline model, take embed (baseline)", take_embed)
bench("custom vjp segsum bf16", partial(sorted_embed, grad_mode="plain"))
bench("custom vjp sort+segsum bf16", partial(sorted_embed, grad_mode="sort"))
bench("custom vjp sort+segsum f32", partial(sorted_embed, grad_mode="sort+f32"))
bench("take embed + rbg dropout", take_embed, impl="rbg")
