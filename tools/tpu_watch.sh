#!/usr/bin/env bash
# Poll the TPU tunnel; when it answers, immediately run the ablation matrix
# and the headline bench, streaming results to log files. Detach with:
#   setsid nohup bash tools/tpu_watch.sh > /tmp/tpu_watch.log 2>&1 &
set -u
cd "$(dirname "$0")/.."

# a probe killed by timeout can itself leave the tunnel wedged
# (.claude/skills/verify/SKILL.md gotchas), so: a long initial quiet
# period, then infrequent probes
echo "[tpu_watch] quiet period $(date)"
sleep 900
for i in $(seq 1 60); do
  if timeout 90 python -c "import jax; jax.devices()" >/dev/null 2>&1; then
    echo "[tpu_watch] tunnel up after probe $i: $(date)"
    timeout 2400 python tools/run_tpu_ablation.py > /tmp/ablation_results.txt 2>&1
    echo "[tpu_watch] ablation rc=$? $(date)"
    timeout 600 python bench.py > /tmp/bench_tpu.txt 2>&1
    echo "[tpu_watch] bench rc=$? $(date)"
    exit 0
  fi
  echo "[tpu_watch] probe $i: tunnel still down $(date)"
  sleep 600
done
echo "[tpu_watch] gave up"
