#!/usr/bin/env bash
# Poll the TPU tunnel; when it answers, immediately run the ablation matrix
# and the headline bench, streaming results to log files. Detach with:
#   setsid nohup bash tools/tpu_watch.sh > /tmp/tpu_watch.log 2>&1 &
set -u
cd "$(dirname "$0")/.."

# a probe killed by timeout can itself leave the tunnel wedged
# (.claude/skills/verify/SKILL.md gotchas), so: a long initial quiet
# period, then infrequent probes. TPU_WATCH_QUIET/TPU_WATCH_PROBES bound
# the lifetime — an unbounded watcher left running becomes a stray
# concurrent tunnel client for whoever measures next (e.g. the driver's
# end-of-round bench).
echo "[tpu_watch] quiet period $(date)"
sleep "${TPU_WATCH_QUIET:-900}"
for i in $(seq 1 "${TPU_WATCH_PROBES:-60}"); do
  # bench.py's probe: a real compile+dispatch in a killable subprocess
  # (jax.devices() can answer on a tunnel whose first compile then hangs,
  # observed 2026-07-30) with the shared persistent compile cache
  if timeout 120 python -c "import bench; raise SystemExit(0 if bench._probe_default_backend(90) else 1)" >/dev/null 2>&1; then
    echo "[tpu_watch] tunnel up after probe $i: $(date)"
    # Remaining round-4 queue (2026-07-31: bench re-stamp + --r4 ablation
    # + pool rows already captured in the morning window before the
    # tunnel re-wedged mid-bench_ctx; what's left):
    # -k 60: a wedged tunnel blocks the main thread in a native XLA call,
    # where CPython DEFERS the TERM handler — without the KILL backstop a
    # hung measurement would survive its timeout and hold the device
    # 1. headline bench at the NEW default (mu-bf16 flip landed after the
    #    morning stamp, which ran at f32 moments)
    BENCH_DEADLINE=1200 timeout -k 60 1500 python bench.py > /tmp/bench_tpu.txt 2>&1
    echo "[tpu_watch] bench rc=$? $(date)"
    # 2. component attribution of the 25.3ms step (VERDICT r3 #2);
    #    profile_step prints a partial summary on a delivered TERM
    timeout -k 60 1200 python tools/profile_step.py > /tmp/profile_step.txt 2>&1
    echo "[tpu_watch] profile_step rc=$? $(date)"
    # 2b. lowering matrix A/B: attention {xla,streaming} x encoder
    #     {concat,split} (added after the morning --r4 capture, which
    #     predates both knobs) — 4 combos + 2 winner repeats + winner with
    #     double-buffered sampling x2
    timeout -k 60 2400 python tools/run_tpu_ablation.py --attn-ab > /tmp/attn_ab.txt 2>&1
    echo "[tpu_watch] attn-ab rc=$? $(date)"
    # 3. long-bag full-step rows (the wedge point last time; every row now
    #    runs in its own killable process group inside bench_ctx)
    timeout -k 60 1800 python tools/bench_ctx.py > /tmp/bench_ctx.txt 2>&1
    echo "[tpu_watch] bench_ctx rc=$? $(date)"
    exit 0
  fi
  echo "[tpu_watch] probe $i: tunnel still down $(date)"
  sleep 600
done
echo "[tpu_watch] gave up"
