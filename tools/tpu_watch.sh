#!/usr/bin/env bash
# Poll the TPU tunnel; when it answers, run whatever measurement tasks have
# not yet produced a complete result, and keep polling until every task is
# done or the probe budget runs out — an intermittent tunnel that wedges
# mid-queue gets another shot at the REMAINING tasks on its next window.
# Detach with:
#   setsid nohup bash tools/tpu_watch.sh > /tmp/tpu_watch.log 2>&1 &
set -u
cd "$(dirname "$0")/.."

# a probe killed by timeout can itself leave the tunnel wedged
# (.claude/skills/verify/SKILL.md gotchas), so: a long initial quiet
# period, then infrequent probes. TPU_WATCH_QUIET/TPU_WATCH_PROBES bound
# the lifetime — an unbounded watcher left running becomes a stray
# concurrent tunnel client for whoever measures next (e.g. the driver's
# end-of-round bench).
echo "[tpu_watch] quiet period $(date)"
sleep "${TPU_WATCH_QUIET:-900}"

# TPU_WATCH_DEADLINE (epoch seconds): past it, start no new tasks and stop
# probing — a late-recovering tunnel must be left clean for the driver's
# own end-of-round bench, not contended by a watcher mid-queue.
past_deadline() {
  [ -n "${TPU_WATCH_DEADLINE:-}" ] && [ "$(date +%s)" -ge "$TPU_WATCH_DEADLINE" ]
}

# Completion predicates: a task is done when its output file carries the
# marker its successful run always prints. Re-running a finished task
# wastes a scarce window; re-running a half-finished one is the point.
# Content (not just existence) gates staleness: the bench stamp must be at
# the CURRENT default (mu-bf16 — the detail record is self-describing for
# exactly this reason), so an old f32-default stamp can't satisfy it; the
# sweeps print their markdown table only after the full run, so a wedge
# mid-matrix still re-runs — but a run that FINISHED with some error rows
# registers as done (counting data rows alone could never converge when one
# combo persistently fails, burning every window on re-runs). The table
# marker alone is not enough either: print_table() emits the header even
# when EVERY row errored, and an all-error sweep (half-wedged tunnel) must
# retry on a later healthy window — so done = marker AND >=1 data row.
# (grep -c prints "0" AND exits 1 on zero matches, so `|| echo 0` would
# double-print; capture and default instead)
count_in() { local n; n=$(grep -c "$1" "$2" 2>/dev/null); echo "${n:-0}"; }
bench_done()    { grep -q '"backend": "tpu"' /tmp/bench_tpu.txt 2>/dev/null && \
                  grep -q '"adam_mu_dtype": "bfloat16"' /tmp/bench_tpu.txt 2>/dev/null; }
profile_done()  { grep -q '"attribution"' /tmp/profile_step.txt 2>/dev/null; }
r5_done()       { grep -q '| config | ms/step |' /tmp/r5_ab.txt 2>/dev/null && \
                  [ "$(count_in '"ms_per_step"' /tmp/r5_ab.txt)" -ge 1 ]; }
attn_ab_done()  { grep -q '| config | ms/step |' /tmp/attn_ab.txt 2>/dev/null && \
                  [ "$(count_in '"ms_per_step"' /tmp/attn_ab.txt)" -ge 1 ]; }
# the step family is bench_ctx's reason to exist (pool rows were captured
# in round 4), so done requires at least one STEP data row, not just any
ctx_done()      { grep -q '| kind | batch | bag |' /tmp/bench_ctx.txt 2>/dev/null && \
                  [ "$(count_in '"kind": "step"' /tmp/bench_ctx.txt)" -ge 1 ]; }

all_done() { bench_done && profile_done && r5_done && attn_ab_done && ctx_done; }

# -k 60: a wedged tunnel blocks the main thread in a native XLA call,
# where CPython DEFERS the TERM handler — without the KILL backstop a
# hung measurement would survive its timeout and hold the device
run_queue() {
  if past_deadline; then
    echo "[tpu_watch] deadline passed — not starting tasks $(date)"
    return
  fi
  if ! bench_done; then
    # headline bench at the NEW default (mu-bf16 flip landed after the
    # morning stamp, which ran at f32 moments)
    BENCH_DEADLINE=1200 timeout -k 60 1500 python bench.py > /tmp/bench_tpu.txt 2>&1
    echo "[tpu_watch] bench rc=$? $(date)"
  fi
  if ! profile_done; then
    # component attribution of the 25.3ms step (VERDICT r3 #2);
    # profile_step prints a partial summary on a delivered TERM
    timeout -k 60 1200 python tools/profile_step.py > /tmp/profile_step.txt 2>&1
    echo "[tpu_watch] profile_step rc=$? $(date)"
  fi
  if ! r5_done; then
    # table-optimizer A/B: dense vs lazy (touched-rows SparseAdam) x2 on
    # the winner recipe + one long-bag point — the round-5 structural
    # lever for the full-table grad + Adam RMW traffic (VERDICT r4 #2)
    timeout -k 60 2400 python tools/run_tpu_ablation.py --r5 > /tmp/r5_ab.txt 2>&1
    echo "[tpu_watch] r5 rc=$? $(date)"
  fi
  if ! attn_ab_done; then
    # lowering matrix A/B: attention {xla,streaming} x encoder
    # {concat,split} — 4 combos + 2 winner repeats + winner/prefetch x2
    timeout -k 60 2400 python tools/run_tpu_ablation.py --attn-ab > /tmp/attn_ab.txt 2>&1
    echo "[tpu_watch] attn-ab rc=$? $(date)"
  fi
  if ! ctx_done; then
    # long-bag full-step rows (every row runs in its own killable
    # process group inside bench_ctx)
    timeout -k 60 1800 python tools/bench_ctx.py > /tmp/bench_ctx.txt 2>&1
    echo "[tpu_watch] bench_ctx rc=$? $(date)"
  fi
}

for i in $(seq 1 "${TPU_WATCH_PROBES:-60}"); do
  if all_done; then
    echo "[tpu_watch] all tasks complete $(date)"
    exit 0
  fi
  if past_deadline; then
    echo "[tpu_watch] deadline passed — exiting to leave the tunnel clean $(date)"
    exit 0
  fi
  # bench.py's probe: a real compile+dispatch in a killable subprocess
  # (jax.devices() can answer on a tunnel whose first compile then hangs,
  # observed 2026-07-30) with the shared persistent compile cache
  if timeout 120 python -c "import bench; raise SystemExit(0 if bench._probe_default_backend(90) else 1)" >/dev/null 2>&1; then
    echo "[tpu_watch] tunnel up after probe $i: $(date)"
    run_queue
    if all_done; then
      echo "[tpu_watch] all tasks complete $(date)"
      exit 0
    fi
    echo "[tpu_watch] queue incomplete (wedge mid-run?) — resuming polls $(date)"
  else
    echo "[tpu_watch] probe $i: tunnel still down $(date)"
  fi
  sleep 600
done
echo "[tpu_watch] gave up"
