"""Ablation inside a scanned chunk (donated state, unique calls): find what
dominates the ~20.5ms/step."""

import time
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
import optax

from code2vec_tpu.models.code2vec import Code2Vec, Code2VecConfig
from code2vec_tpu.train.step import weighted_nll, torch_style_adam, TrainState

B, L = 1024, 200
mc = Code2VecConfig(
    terminal_count=360_633, path_count=342_846, label_count=8_000,
    terminal_embed_size=100, path_embed_size=100, encode_size=100,
    dropout_prob=0.25, dtype=jnp.bfloat16)

rng = np.random.default_rng(0)
batch = {
    "starts": jax.device_put(rng.integers(1, mc.terminal_count, (B, L)).astype(np.int32)),
    "paths": jax.device_put(rng.integers(1, mc.path_count, (B, L)).astype(np.int32)),
    "ends": jax.device_put(rng.integers(1, mc.terminal_count, (B, L)).astype(np.int32)),
    "labels": jax.device_put(rng.integers(0, mc.label_count, B).astype(np.int32)),
    "example_mask": jax.device_put(np.ones(B, np.float32)),
}
model = Code2Vec(mc)
cw = jnp.ones(mc.label_count, jnp.float32)
params = model.init({"params": jax.random.PRNGKey(0)}, batch["starts"],
                    batch["paths"], batch["ends"], deterministic=True)["params"]


def make_step(tx, freeze_embeds=False, fwd_only=False, no_dropout=False):
    def loss_fn(p, batch, key):
        if freeze_embeds:
            p = dict(p)
            for k in ("terminal_embedding", "path_embedding"):
                p[k] = jax.tree.map(jax.lax.stop_gradient, p[k])
        logits, _, _ = model.apply(
            {"params": p}, batch["starts"], batch["paths"], batch["ends"],
            deterministic=no_dropout, rngs={} if no_dropout else {"dropout": key})
        return weighted_nll(logits, batch["labels"], cw, batch["example_mask"])

    def step(state, batch):
        key, nxt = jax.random.split(state.dropout_rng)
        if fwd_only:
            loss = loss_fn(state.params, batch, key)
            return state.replace(dropout_rng=nxt), loss
        loss, grads = jax.value_and_grad(loss_fn)(state.params, batch, key)
        state = state.apply_gradients(grads=grads, dropout_rng=nxt)
        return state, loss
    return step


def bench(name, tx, n_scan=10, reps=6, **kw):
    fresh = jax.tree.map(jnp.copy, params)  # params get donated per-bench
    state = TrainState.create(apply_fn=model.apply, params=fresh, tx=tx,
                              dropout_rng=jax.random.PRNGKey(1))
    step = make_step(tx, **kw)

    @partial(jax.jit, donate_argnums=0)
    def chunk(state, batch):
        def body(s, _):
            return step(s, batch)
        state, losses = jax.lax.scan(body, state, None, length=n_scan)
        return state, losses.sum()

    state, l = chunk(state, batch)
    jax.block_until_ready(l)
    t0 = time.perf_counter()
    for _ in range(reps):
        state, l = chunk(state, batch)
    jax.block_until_ready(l)
    dt = (time.perf_counter() - t0) / (reps * n_scan) * 1e3
    print(f"{name:44s} {dt:8.3f} ms/step")


adam = torch_style_adam(0.01, 0.9, 0.999, 0.0)
sgd = optax.sgd(0.01)

bench("full step, adam (baseline)", adam)
bench("full step, sgd", sgd)
bench("frozen embeddings, adam", adam, freeze_embeds=True)
bench("frozen embeddings, sgd", sgd, freeze_embeds=True)
bench("forward only", sgd, fwd_only=True)
bench("forward only, no dropout", sgd, fwd_only=True, no_dropout=True)
