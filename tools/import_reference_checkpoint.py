"""Import a trained checkpoint from the reference implementation.

The reference saves ``torch.save(model.state_dict(), <model_path>/
code2vec.model)`` on every new best F1 (reference main.py:231). This tool
converts that file into a checkpoint of THIS framework — so a user
switching over keeps their trained models, not just their datasets:

    python tools/import_reference_checkpoint.py \
        --reference_model /path/to/output/code2vec.model \
        --corpus_path corpus.txt \
        --terminal_idx_path terminal_idxs.txt \
        --path_idx_path path_idxs.txt \
        --model_path out/

``out/`` then works everywhere a trained model dir does: `predict`,
`--export_only` vector export, eval, or resumed fine-tuning (optimizer
moments start fresh — the reference checkpoint has none).

The corpus/vocab files must be the ones the checkpoint was trained with:
the label vocabulary is rebuilt from the corpus in the reference's
insertion order (our reader reproduces it bit-for-bit — data/reader.py),
and every tensor dimension is cross-checked against the state_dict before
anything is written.

Parameter mapping (reference model/model.py:21-42 → models/code2vec.py):

    terminal_embedding.weight [T, dt]  → terminal_embedding.embedding
    path_embedding.weight     [P, dp]  → path_embedding.embedding
    input_linear.weight   [E, 2dt+dp]  → input_dense.kernel (TRANSPOSED —
                                         torch Linear stores [out, in];
                                         concat order start|path|end is
                                         the same on both sides)
    input_layer_norm.weight/bias  [E]  → input_layer_norm.scale/bias
    attention_parameter           [E]  → attention
    output_linear.weight/bias (plain)  → output_dense.kernel (T)/bias
    output_linear (margin Parameter)   → output_margin_weight

After conversion the tool runs BOTH forwards (torch in eval mode vs our
model, deterministic) on a probe batch from the corpus and refuses to
write unless the logits agree to --atol (default 2e-4 — f32 reduction
order differs across frameworks; bit-equality is not expected).
"""

from __future__ import annotations

import argparse
import json
import logging
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

logger = logging.getLogger("import_reference_checkpoint")

from code2vec_tpu.interop import (  # noqa: E402 - after sys.path insert
    infer_dims,
    load_state_dict,
    reference_forward,
    to_param_tree,
)


def run_import(args) -> None:
    sd = load_state_dict(args.reference_model)
    dims = infer_dims(sd)
    logger.info("state_dict dims: %s", dims)

    from code2vec_tpu.data.reader import load_corpus

    data = load_corpus(
        args.corpus_path,
        args.path_idx_path,
        args.terminal_idx_path,
        infer_method=args.infer_method_name,
        infer_variable=args.infer_variable_name,
        cache=not args.no_corpus_cache,
    )
    mismatches = [
        (name, have, want)
        for name, have, want in (
            ("terminal vocab", len(data.terminal_vocab), dims["terminal_count"]),
            ("path vocab", len(data.path_vocab), dims["path_count"]),
            ("label vocab", len(data.label_vocab), dims["label_count"]),
        )
        if have != want
    ]
    if mismatches:
        raise SystemExit(
            "corpus/vocab files do not match the checkpoint: "
            + "; ".join(f"{n}: files give {h}, checkpoint has {w}" for n, h, w in mismatches)
            + "\n(pass the exact corpus + idx files the reference trained on,"
            " and the same --infer_method_name/--infer_variable_name flags)"
        )

    import jax
    import jax.numpy as jnp

    from code2vec_tpu.checkpoint import TrainMeta, save_checkpoint
    from code2vec_tpu.data.pipeline import build_method_epoch, iter_batches
    from code2vec_tpu.models.code2vec import Code2VecConfig
    from code2vec_tpu.predict import save_inference_meta
    from code2vec_tpu.train.config import TrainConfig
    from code2vec_tpu.train.step import create_train_state

    model_config = Code2VecConfig(
        terminal_count=dims["terminal_count"],
        path_count=dims["path_count"],
        label_count=dims["label_count"],
        terminal_embed_size=dims["terminal_embed_size"],
        path_embed_size=dims["path_embed_size"],
        encode_size=dims["encode_size"],
        dropout_prob=args.dropout_prob,
        angular_margin_loss=dims["angular_margin_loss"],
        angular_margin=args.angular_margin,
        inverse_temp=args.inverse_temp,
        vocab_pad_multiple=1,
    )
    config = TrainConfig(
        batch_size=min(8, data.n_items),
        max_path_length=args.max_path_length,
        terminal_embed_size=dims["terminal_embed_size"],
        path_embed_size=dims["path_embed_size"],
        encode_size=dims["encode_size"],
        dropout_prob=args.dropout_prob,
        angular_margin_loss=dims["angular_margin_loss"],
        angular_margin=args.angular_margin,
        inverse_temp=args.inverse_temp,
        infer_method_name=args.infer_method_name,
        infer_variable_name=args.infer_variable_name,
    )

    rng = np.random.default_rng(0)
    probe_items = np.arange(min(8, data.n_items))
    epoch = build_method_epoch(data, probe_items, args.max_path_length, rng)
    batch = next(iter_batches(epoch, len(probe_items), rng=rng, pad_final=False))
    # with --infer_method_name False the method labels are -1 (unused for
    # training); the margin head's one-hot needs a valid class on BOTH
    # sides of the probe, and which class it is does not affect parity —
    # clamp to 0 for the probe only
    batch = dict(batch, labels=np.maximum(np.asarray(batch["labels"]), 0))
    state = create_train_state(
        config, model_config, jax.random.PRNGKey(0), batch
    )

    tree = jax.tree.map(jnp.asarray, to_param_tree(sd, dims))
    init_shapes = jax.tree.map(jnp.shape, state.params)
    got_shapes = jax.tree.map(jnp.shape, tree)
    if init_shapes != got_shapes:
        raise SystemExit(
            f"converted tree does not match the model:\n  model: "
            f"{init_shapes}\n  converted: {got_shapes}"
        )
    state = state.replace(params=tree)

    # the probe: both forwards on a real batch, eval mode
    ours, _cv, _attn = state.apply_fn(
        {"params": state.params},
        batch["starts"], batch["paths"], batch["ends"],
        labels=batch["labels"], deterministic=True,
    )
    theirs = reference_forward(
        sd, dims,
        np.asarray(batch["starts"]), np.asarray(batch["paths"]),
        np.asarray(batch["ends"]), np.asarray(batch["labels"]),
        args.angular_margin, args.inverse_temp,
    )
    diff = float(np.max(np.abs(np.asarray(ours, np.float32) - theirs)))
    logger.info("probe max |Δlogits| vs the reference forward: %.3g", diff)
    if diff > args.atol:
        raise SystemExit(
            f"imported forward disagrees with the reference: max |Δ| = "
            f"{diff:.3g} > atol {args.atol:.3g} — refusing to write"
        )

    os.makedirs(args.model_path, exist_ok=True)
    meta = TrainMeta(
        epoch=0,
        best_f1=None,
        rng_impl=config.rng_impl,
        vocab_pad_multiple=1,
    )
    path = save_checkpoint(args.model_path, state, meta, slot="best")
    save_inference_meta(args.model_path, config, model_config, data)
    print(
        json.dumps(
            {
                "imported": os.path.abspath(path),
                "probe_max_abs_logit_diff": diff,
                **dims,
            }
        )
    )


def main(argv=None) -> None:
    parser = argparse.ArgumentParser(
        description="Convert a reference code2vec.model (torch state_dict) "
        "into a checkpoint of this framework."
    )
    parser.add_argument(
        "--reference_model", required=True,
        help="path to code2vec.model (or the directory containing it)",
    )
    parser.add_argument("--corpus_path", required=True)
    parser.add_argument("--terminal_idx_path", required=True)
    parser.add_argument("--path_idx_path", required=True)
    parser.add_argument("--model_path", required=True, help="output dir")
    parser.add_argument("--max_path_length", type=int, default=200)
    parser.add_argument("--dropout_prob", type=float, default=0.25)
    # runtime constants of the margin head — not stored in the state_dict
    # (reference main.py:74-75 defaults)
    parser.add_argument("--angular_margin", type=float, default=0.5)
    parser.add_argument("--inverse_temp", type=float, default=30.0)
    from code2vec_tpu.cli import _strtobool

    # same parser as the main CLI: "true"/"1"/"yes" all work, bad values
    # error loudly instead of silently flipping the label vocab
    parser.add_argument("--infer_method_name", type=_strtobool, default=True)
    parser.add_argument("--infer_variable_name", type=_strtobool, default=False)
    parser.add_argument("--no_corpus_cache", action="store_true")
    parser.add_argument("--atol", type=float, default=2e-4)
    args = parser.parse_args(argv)

    logging.basicConfig(level=logging.INFO, format="%(message)s")
    # inference-scale work: pin CPU like predict does (the ambient
    # JAX_PLATFORMS may point at a cold/wedged device tunnel)
    from code2vec_tpu.cli import pin_platform

    pin_platform(True)
    run_import(args)


if __name__ == "__main__":
    main()
