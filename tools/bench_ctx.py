"""Long-bag / ctx-axis benchmark (SURVEY §5.7; the reference caps bags at
200 — main.py:48's max_path_length — so everything past bag 200 is regime
this framework adds).

Two measurement families, single chip:

1. ``pool``: the attention pooling op in isolation — forward + backward of
   the masked softmax + weighted sum — comparing the plain XLA chain
   (ops/attention.py) against the explicit streaming-softmax shard_map
   variant (parallel/context.py) on a 1-device ctx mesh, where its pmax /
   psum collectives are no-ops. Parity of the two timings shows the
   ctx-parallel building block adds no single-chip overhead; the multi-chip
   ctx split itself stays staged until hardware with >1 chip is available
   (the dryrun validates it compiles + executes on the virtual mesh).

2. ``step``: the full flagship train step (EpochRunner scanned chunks, the
   same path bench.py measures) at lifted-cap bag sizes, batch scaled to
   hold B x L context slots roughly constant, on a synthetic corpus whose
   per-method context counts actually fill the long bags (mean 0.8 x bag)
   — top11 vocabs, so the embedding tables stay at production scale.

Prints one JSON line per row plus a markdown table for docs/ARCHITECTURE.md.
Usage: python tools/bench_ctx.py [--quick]
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import sys
import time

import numpy as np

_HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, os.path.dirname(_HERE))  # repo root: the package
sys.path.insert(0, _HERE)  # tools/: run_tpu_ablation's measure_step


def _pin_platform() -> None:
    """The experimental axon plugin pre-empts the JAX_PLATFORMS env var
    (verify SKILL gotchas) — an operator's JAX_PLATFORMS=cpu would silently
    hit the tunnel. Re-assert the env choice via the reliable config API."""
    plat = os.environ.get("JAX_PLATFORMS", "").strip()
    if plat:
        import jax

        jax.config.update("jax_platforms", plat)


def _time_it(fn, *args, warmup: int = 2, iters: int = 20) -> float:
    import jax

    for _ in range(warmup):
        out = fn(*args)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(*args)
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / iters * 1e3


def measure_pool(batch: int, bag: int, encode: int = 100) -> dict:
    """ms for forward+backward of the pooling op: XLA vs streaming."""
    import jax
    import jax.numpy as jnp

    from code2vec_tpu.ops.attention import attention_pool
    from code2vec_tpu.parallel.context import context_parallel_attention_pool
    from code2vec_tpu.parallel.mesh import make_mesh

    rng = np.random.default_rng(0)
    contexts = jnp.asarray(rng.standard_normal((batch, bag, encode)), jnp.float32)
    mask = jnp.asarray(rng.random((batch, bag)) < 0.9, jnp.float32)
    attn = jnp.asarray(rng.standard_normal(encode), jnp.float32)

    def xla_loss(contexts, attn):
        cv, _ = attention_pool(contexts, mask, attn)
        return jnp.sum(cv * cv)

    mesh = make_mesh(data=1, model=1, ctx=1, devices=jax.devices()[:1])

    def stream_loss(contexts, attn):
        cv, _ = context_parallel_attention_pool(mesh, contexts, mask, attn)
        return jnp.sum(cv * cv)

    xla_fb = jax.jit(jax.value_and_grad(xla_loss, argnums=(0, 1)))
    stream_fb = jax.jit(jax.value_and_grad(stream_loss, argnums=(0, 1)))
    return {
        "xla_ms": round(_time_it(xla_fb, contexts, attn), 3),
        "streaming_ms": round(_time_it(stream_fb, contexts, attn), 3),
    }


def measure_long_bag_step(batch: int, bag: int, steps: int = 32) -> float:
    """ms/step of the flagship scanned-chunk path at a lifted-cap bag size,
    on a corpus whose methods actually have ~0.8 x bag contexts each.
    Delegates to run_tpu_ablation.measure_step (the one timing harness) with
    the round-3 winner recipe and a long-bag synth spec."""
    import jax

    from run_tpu_ablation import measure_step

    return measure_step(
        jax,
        embed_grad="dense", rng_impl="unsafe_rbg", dtype_name="f32",
        batch=batch, bag=bag, chunk=8, steps=steps,
        n_methods=max(batch * 4, 1024),
        mean_contexts=0.8 * bag, max_contexts=2 * bag,
    )


# The in-flight row child, for the parent's own signal handler: the rows
# run in their own sessions (so a wedge is killable without killing the
# parent), which also detaches them from the watcher's `timeout -k` — a
# TERM/KILL aimed at this parent would otherwise orphan a wedged child on
# the tunnel indefinitely.
_CURRENT_CHILD = None


def _kill_current_child() -> None:
    proc = _CURRENT_CHILD
    if proc is not None and proc.poll() is None:
        try:
            os.killpg(proc.pid, signal.SIGKILL)
        except (ProcessLookupError, PermissionError):
            pass
        proc.wait()


def _on_term(signum, frame):  # noqa: ARG001 - signal handler signature
    # No proc.wait() here: the signal usually interrupts the main thread
    # inside proc.wait(timeout=...), which holds Popen's non-reentrant
    # _waitpid_lock — waiting again on the same thread would deadlock
    # (bench.py's _kill_tree lesson). Raw killpg, then a hard exit; the
    # child is SIGKILLed so there is nothing to reap that init won't take.
    proc = _CURRENT_CHILD
    if proc is not None:
        try:
            os.killpg(proc.pid, signal.SIGKILL)
        except (ProcessLookupError, PermissionError):
            pass
    os._exit(128 + signum)


def _run_row_subprocess(mode: str, batch: int, bag: int,
                        timeout_s: float) -> dict:
    """One measurement row in a killable child. The child gets its own
    process group and on timeout the WHOLE group is SIGKILLed — a wedged
    tunnel compile can hang forever, and plugin helper processes holding
    the captured pipes would otherwise keep a plain subprocess.run blocked
    in communicate() past its timeout (bench.py's _kill_tree lesson).
    Output goes to a temp file, not a pipe, for the same reason."""
    global _CURRENT_CHILD
    import subprocess
    import tempfile

    with tempfile.TemporaryFile("w+") as out_f, \
            tempfile.TemporaryFile("w+") as err_f:
        # block TERM/INT across spawn+assignment: a signal landing between
        # Popen returning and _CURRENT_CHILD being set would let _on_term
        # exit without killing the just-spawned session-detached child
        masked = {signal.SIGTERM, signal.SIGINT}
        signal.pthread_sigmask(signal.SIG_BLOCK, masked)
        try:
            proc = subprocess.Popen(
                [sys.executable, os.path.abspath(__file__),
                 f"--{mode}-row", str(batch), str(bag)],
                stdout=out_f, stderr=err_f, start_new_session=True,
            )
            _CURRENT_CHILD = proc
        finally:
            signal.pthread_sigmask(signal.SIG_UNBLOCK, masked)
        try:
            proc.wait(timeout=timeout_s)
        except subprocess.TimeoutExpired:
            _kill_current_child()
            return {"error": f"timeout {timeout_s}s (tunnel wedge?)"}
        finally:
            _CURRENT_CHILD = None
        out_f.seek(0)
        err_f.seek(0)
        stdout, stderr = out_f.read(), err_f.read()
    try:
        line = next(
            l for l in reversed(stdout.splitlines())
            if l.startswith("{") and '"kind"' in l
        )
        return json.loads(line)
    except Exception:  # noqa: BLE001 - child died before a row line
        # surface the child's own structured error row when it printed one
        for l in reversed(stdout.splitlines()):
            if l.startswith("{") and '"error"' in l:
                try:
                    return json.loads(l)
                except Exception:  # noqa: BLE001 - not JSON after all
                    break
        return {"error": f"rc={proc.returncode} {stderr[-250:]}"}


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true")
    ap.add_argument(
        "--step-row", nargs=2, type=int, metavar=("BATCH", "BAG"),
        default=None,
        help="internal: measure ONE long-bag step row and print its JSON "
        "line (the parent runs each row in a killable subprocess so a "
        "tunnel wedge costs one row's timeout, not the rest of the run "
        "— the 2026-07-31 window died mid-run)",
    )
    ap.add_argument(
        "--pool-row", nargs=2, type=int, metavar=("BATCH", "BAG"),
        default=None, help="internal: measure ONE pool row (see --step-row)",
    )
    ap.add_argument(
        "--row-timeout", type=float, default=600.0,
        help="per-row subprocess budget, seconds (additionally capped by "
        "the remaining --total-budget, so a slow early row shrinks later "
        "rows instead of blowing the whole run's deadline)",
    )
    ap.add_argument(
        "--total-budget", type=float,
        default=float(os.environ.get("BENCH_CTX_BUDGET", 1680.0)),
        help="whole-run budget, seconds (default 1680 = the watcher's "
        "outer `timeout -k 60 1800` minus startup slack); rows that no "
        "longer fit are skipped with an error row and the summary table "
        "still prints, so a finished-but-slow sweep isn't discarded",
    )
    args = ap.parse_args()

    if args.step_row is not None:
        _pin_platform()
        batch, bag = args.step_row
        try:
            ms = measure_long_bag_step(batch, bag)
        except Exception as e:  # noqa: BLE001 - structured row for the parent
            print(json.dumps({"batch": batch, "bag": bag,
                              "error": str(e)[:300]}), flush=True)
            raise SystemExit(1)
        print(json.dumps({
            "kind": "step", "batch": batch, "bag": bag,
            "ms_per_step": round(ms, 3),
            "contexts_per_sec": round(batch * bag / ms * 1e3, 0),
        }), flush=True)
        return

    if args.pool_row is not None:
        _pin_platform()
        batch, bag = args.pool_row
        try:
            row = measure_pool(batch, bag)
        except Exception as e:  # noqa: BLE001 - structured row for the parent
            print(json.dumps({"batch": batch, "bag": bag,
                              "error": str(e)[:300]}), flush=True)
            raise SystemExit(1)
        print(json.dumps({
            "kind": "pool", "batch": batch, "bag": bag, **row,
        }), flush=True)
        return

    # parent mode: a TERM from the watcher's outer timeout must take the
    # in-flight row child down with us (it lives in its own session, so
    # nothing else will)
    signal.signal(signal.SIGTERM, _on_term)
    signal.signal(signal.SIGINT, _on_term)

    _pin_platform()
    t0 = time.monotonic()
    import jax

    print(json.dumps({"backend": jax.default_backend()}), flush=True)

    rows = []
    # full step at lifted caps FIRST: the pool rows are cheap and were
    # already captured in the 2026-07-31 window — the step family is the
    # data a tight window must not miss
    step_shapes = [(256, 1024)] if args.quick else [
        (1024, 200), (256, 1024), (64, 4096),
    ]
    # pool microbench: B x L held at ~256k slots
    pool_shapes = [(1024, 200), (256, 1024)] if args.quick else [
        (1024, 200), (256, 1024), (64, 4096),
    ]
    for mode, batch, bag in (
        [("step", b, g) for b, g in step_shapes]
        + [("pool", b, g) for b, g in pool_shapes]
    ):
        # a row needs a realistic floor (tunnel compile alone is 20-40s,
        # and SIGKILLing a mid-compile child is itself a wedge risk —
        # tools/tpu_watch.sh's header) — skip rather than launch doomed
        remaining = args.total_budget - (time.monotonic() - t0)
        if remaining - 30 < 150:
            print(json.dumps({mode: f"b{batch}/bag{bag}",
                              "error": "skipped: total budget exhausted"}),
                  flush=True)
            continue
        row_timeout = min(args.row_timeout, remaining - 30)
        row = _run_row_subprocess(mode, batch, bag, row_timeout)
        if "error" in row:
            if (row_timeout < args.row_timeout
                    and row["error"].startswith("timeout")):
                row["error"] += " [budget-capped, not the row's full timeout]"
            print(json.dumps({mode: f"b{batch}/bag{bag}", **row}), flush=True)
            continue
        rows.append(row)
        print(json.dumps(row), flush=True)

    print("\n| kind | batch | bag | ms (xla / streaming or step) | ctx/s |")
    print("|---|---|---|---|---|")
    for r in rows:
        if r["kind"] == "pool":
            ms = f"{r['xla_ms']} / {r['streaming_ms']}"
            cs = ""
        else:
            ms = f"{r['ms_per_step']}"
            cs = f"{int(r['contexts_per_sec']):,}"
        print(f"| {r['kind']} | {r['batch']} | {r['bag']} | {ms} | {cs} |")


if __name__ == "__main__":
    main()
