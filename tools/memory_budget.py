"""Measure host memory of the epoch paths at top11 scale (605k methods) and
print the documented java-large budget (BASELINE config 3, 16M methods).

Usage: python tools/memory_budget.py [--materialize]

Default: stream a partial epoch (first N chunks) with
``iter_streaming_batches`` and report peak RSS delta. ``--materialize``
builds the full ``[N, L]`` epoch instead (the path streaming replaces) for
comparison. Run each mode in a fresh process; RSS is process-wide.
"""

from __future__ import annotations

import argparse
import json
import resource
import sys

import numpy as np

sys.path.insert(0, ".")

from code2vec_tpu.data.pipeline import build_epoch, iter_streaming_batches  # noqa: E402
from code2vec_tpu.data.synth import (  # noqa: E402
    SynthSpec,
    corpus_data_from_raw,
    generate_corpus_data,
)


def rss_mb() -> float:
    return resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1024.0


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--materialize", action="store_true")
    ap.add_argument("--n_methods", type=int, default=605_945)  # top11 scale
    ap.add_argument("--bag", type=int, default=200)
    ap.add_argument("--chunk_items", type=int, default=65_536)
    ap.add_argument("--batches", type=int, default=96, help="stream this many")
    args = ap.parse_args()

    spec = SynthSpec(
        n_methods=args.n_methods,
        n_terminals=360_631,
        n_paths=342_845,
        n_labels=8_000,
        mean_contexts=120.0,
        max_contexts=400,
        seed=0,
    )
    data = corpus_data_from_raw(generate_corpus_data(spec))
    base = rss_mb()
    rng = np.random.default_rng(0)
    idx = np.arange(data.n_items)

    if args.materialize:
        epoch = build_epoch(data, idx, args.bag, rng)
        mode = "materialize"
        touched = len(epoch)
    else:
        builder = lambda i: build_epoch(data, i, args.bag, rng)  # noqa: E731
        it = iter_streaming_batches(
            builder, idx, batch_size=1024, rng=rng, chunk_items=args.chunk_items
        )
        touched = 0
        for _ in range(args.batches):
            next(it)
            touched += 1024
        mode = "stream"

    print(
        json.dumps(
            {
                "mode": mode,
                "n_methods": args.n_methods,
                "bag": args.bag,
                "corpus_rss_mb": round(base, 1),
                "epoch_peak_delta_mb": round(rss_mb() - base, 1),
                "rows_touched": touched,
            }
        )
    )


if __name__ == "__main__":
    main()
