#!/usr/bin/env python
"""Thin wrapper for the static-analysis runner; equivalent to
``python -m code2vec_tpu.analysis`` (see that module for flags). Kept as
a tool entry point so `tools/` is the one place operators look for
repo drives. Pure stdlib — runs without the jax environment."""

import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

from code2vec_tpu.analysis.__main__ import main  # noqa: E402

if __name__ == "__main__":
    raise SystemExit(main())
