"""Export a checkpoint of THIS framework to the reference's format.

The inverse of ``tools/import_reference_checkpoint.py``: converts a
trained model dir (orbax checkpoint + ``model_meta.json``) into the
``code2vec.model`` torch state_dict the reference's
``torch.save(model.state_dict(), ...)`` produces (reference main.py:231)
— so models trained here can be served or fine-tuned by existing torch
infrastructure, completing the two-way migration story:

    python tools/export_reference_checkpoint.py \
        --model_path out/ \
        --output /path/to/refout/code2vec.model

Dims and head type come from ``model_meta.json`` (written at train time,
or by the import tool); vocab-pad rows/head columns beyond the true
vocab sizes are sliced off — exact, because pad ids never occur in data
(see code2vec_tpu/interop.py). Before writing, the tool replays the
reference forward (torch, eval mode) against ours on a random probe
batch and refuses unless the logits agree to --atol.
"""

from __future__ import annotations

import argparse
import json
import logging
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

logger = logging.getLogger("export_reference_checkpoint")

from code2vec_tpu.interop import (  # noqa: E402 - after sys.path insert
    from_param_tree,
    infer_dims,
    reference_forward,
    save_state_dict,
)


def run_export(args) -> None:
    meta_file = os.path.join(args.model_path, "model_meta.json")
    if not os.path.exists(meta_file):
        raise SystemExit(
            f"{meta_file} not found — the model dir must come from a train "
            "run (or tools/import_reference_checkpoint.py), which persists "
            "the model dims there"
        )
    with open(meta_file) as f:
        meta = json.load(f)

    import jax
    import jax.numpy as jnp

    from code2vec_tpu.checkpoint import restore_checkpoint
    from code2vec_tpu.models.code2vec import Code2VecConfig
    from code2vec_tpu.train.config import TrainConfig
    from code2vec_tpu.train.step import create_train_state

    model_config = Code2VecConfig(
        terminal_count=meta["terminal_count"],
        path_count=meta["path_count"],
        label_count=meta["label_count"],
        terminal_embed_size=meta["terminal_embed_size"],
        path_embed_size=meta["path_embed_size"],
        encode_size=meta["encode_size"],
        angular_margin_loss=meta["angular_margin_loss"],
        angular_margin=meta["angular_margin"],
        inverse_temp=meta["inverse_temp"],
        vocab_pad_multiple=meta.get("vocab_pad_multiple") or 1,
    )
    config = TrainConfig(
        batch_size=4,
        max_path_length=meta.get("max_path_length", 200),
        rng_impl=meta.get("rng_impl", "threefry2x32"),
        adam_mu_dtype=meta.get("adam_mu_dtype", "float32"),
    )

    # a synthetic probe batch is enough: the probe compares the two
    # forwards on the SAME inputs, it does not need real data
    rng = np.random.default_rng(0)
    bag = min(32, config.max_path_length)
    batch = {
        "starts": rng.integers(
            1, meta["terminal_count"], (4, bag), dtype=np.int32
        ),
        "paths": rng.integers(1, meta["path_count"], (4, bag), dtype=np.int32),
        "ends": rng.integers(
            1, meta["terminal_count"], (4, bag), dtype=np.int32
        ),
        "labels": rng.integers(0, meta["label_count"], (4,), dtype=np.int32),
        "example_mask": np.ones((4,), np.float32),
    }
    batch["starts"][:, bag // 2:] = 0  # exercise the padding mask too

    template = create_train_state(
        config, model_config, jax.random.PRNGKey(0), batch
    )
    restored = restore_checkpoint(
        args.model_path, template,
        vocab_pad_multiple=model_config.vocab_pad_multiple,
        prefer_best=True,
    )
    if restored is None:
        raise SystemExit(f"no checkpoint found under {args.model_path}")
    state, _train_meta = restored

    sd = from_param_tree(jax.tree.map(np.asarray, state.params), model_config)
    # re-derive dims from the converted tensors: catches a model_meta.json
    # that disagrees with the checkpoint with a clear message instead of a
    # confusing layer_norm shape error in the probe
    dims = infer_dims(sd)
    for key in ("encode_size", "angular_margin_loss", "label_count"):
        if dims[key] != meta[key]:
            raise SystemExit(
                f"model_meta.json disagrees with the checkpoint: {key} is "
                f"{meta[key]} in the meta but {dims[key]} in the tensors"
            )

    ours, _cv, _attn = state.apply_fn(
        {"params": state.params},
        jnp.asarray(batch["starts"]), jnp.asarray(batch["paths"]),
        jnp.asarray(batch["ends"]),
        labels=jnp.asarray(batch["labels"]), deterministic=True,
    )
    theirs = reference_forward(
        sd, dims,
        batch["starts"], batch["paths"], batch["ends"], batch["labels"],
        meta["angular_margin"], meta["inverse_temp"],
    )
    diff = float(np.max(np.abs(np.asarray(ours, np.float32) - theirs)))
    logger.info("probe max |Δlogits| vs the reference forward: %.3g", diff)
    if diff > args.atol:
        raise SystemExit(
            f"exported forward disagrees with this checkpoint: max |Δ| = "
            f"{diff:.3g} > atol {args.atol:.3g} — refusing to write"
        )

    out_dir = os.path.dirname(os.path.abspath(args.output))
    os.makedirs(out_dir, exist_ok=True)
    path = save_state_dict(sd, args.output)
    print(
        json.dumps(
            {
                "exported": os.path.abspath(path),
                "probe_max_abs_logit_diff": diff,
                "terminal_count": meta["terminal_count"],
                "path_count": meta["path_count"],
                "label_count": meta["label_count"],
                "angular_margin_loss": meta["angular_margin_loss"],
            }
        )
    )


def main(argv=None) -> None:
    parser = argparse.ArgumentParser(
        description="Convert a trained model dir of this framework into the "
        "reference's code2vec.model torch state_dict."
    )
    parser.add_argument(
        "--model_path", required=True,
        help="trained model dir (checkpoint + model_meta.json)",
    )
    parser.add_argument(
        "--output", required=True,
        help="output file (conventionally <dir>/code2vec.model)",
    )
    parser.add_argument("--atol", type=float, default=2e-4)
    args = parser.parse_args(argv)

    logging.basicConfig(level=logging.INFO, format="%(message)s")
    from code2vec_tpu.cli import pin_platform

    pin_platform(True)
    run_export(args)


if __name__ == "__main__":
    main()
