"""Convert a text corpus to the memory-mapped CSR container (and back).

The CSR container (formats/corpus_io.py) is the out-of-core corpus format:
context arrays live as flat on-disk sections that training gathers through
mmap views (``--corpus_format csr``), so corpora larger than host RAM feed
bucketed/prefetched/multi-host runs in bounded RSS. The conversion streams —
peak converter RSS is O(n_items + strings), never O(contexts).

Terminal start/end ids are stored pre-shifted by ``@question``'s +1 (the
shift the dataset reader applies per run on the text path) so mmap feeding
is zero-copy; the reverse conversion subtracts it, making

    python tools/corpus_convert.py corpus.txt corpus.csr
    python tools/corpus_convert.py --to-text corpus.csr roundtrip.txt

byte-faithful for canonically-written corpora (``formats.corpus_io
.write_corpus`` output — which includes the synth generator and the
extractor): ``roundtrip.txt`` is byte-identical to ``corpus.txt``.

The container footer carries the context-count histogram; inspect it with
``tools/corpus_stats.py corpus.csr`` (no context scan).
"""

from __future__ import annotations

import argparse
import os
import sys
import time

_HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, os.path.dirname(_HERE))  # repo root: the package

from code2vec_tpu import QUESTION_TOKEN_INDEX  # noqa: E402
from code2vec_tpu.formats.corpus_io import (  # noqa: E402
    CsrCorpusWriter,
    is_csr_corpus,
    iter_corpus_records,
    open_corpus_csr,
    write_corpus_record,
)


def text_to_csr(src: str, dst: str, shift: int = QUESTION_TOKEN_INDEX) -> int:
    """Stream ``src`` (text corpus) into ``dst`` (CSR container); returns
    the record count."""
    n = 0
    with CsrCorpusWriter(dst, terminal_shift=shift) as writer:
        for record in iter_corpus_records(src):
            writer.add(record)
            n += 1
    return n


def csr_to_text(src: str, dst: str) -> int:
    """Stream ``src`` (CSR container) back to the canonical text form."""
    with open_corpus_csr(src) as corpus:
        with open(dst, "w", encoding="utf-8") as f:
            for record in corpus.iter_records():
                write_corpus_record(f, record)
        return corpus.n_items


def main(argv: list[str] | None = None) -> None:
    parser = argparse.ArgumentParser(
        description="text corpus <-> memory-mapped CSR container"
    )
    parser.add_argument("src", help="input corpus (text, or CSR with --to-text)")
    parser.add_argument("dst", help="output path")
    parser.add_argument(
        "--to-text",
        action="store_true",
        default=False,
        help="convert a CSR container back to canonical text "
        "(default: text -> CSR)",
    )
    args = parser.parse_args(argv)

    t0 = time.perf_counter()
    if args.to_text:
        if not is_csr_corpus(args.src):
            raise SystemExit(f"{args.src!r} is not a CSR container")
        n = csr_to_text(args.src, args.dst)
        direction = "csr -> text"
    else:
        if is_csr_corpus(args.src):
            raise SystemExit(
                f"{args.src!r} is already a CSR container; did you mean "
                "--to-text?"
            )
        n = text_to_csr(args.src, args.dst)
        direction = "text -> csr"
    print(
        f"{direction}: {n} records, {os.path.getsize(args.dst)} bytes "
        f"in {time.perf_counter() - t0:.1f}s -> {args.dst}"
    )


if __name__ == "__main__":
    main()
